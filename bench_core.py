"""Core-runtime microbenchmarks.

Reference: ``python/ray/_private/ray_perf.py:93-241`` +
``release/microbenchmark/run_microbenchmark.py`` — the accountability
instrument for core throughput properties (single in-flight task per worker,
lease path, GCS-central directory, channel hops). Runs against a real
in-process cluster (GCS + node manager + OS worker processes), prints one
JSON line per metric, and writes ``BENCH_CORE_r{N}.json``.

Usage: python bench_core.py [--round N] [--quick]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def timed(fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    return time.perf_counter() - t0, out


def bench_tasks_per_s(ray_tpu, n):
    @ray_tpu.remote(num_cpus=0)
    def nop():
        return 0

    ray_tpu.get(nop.remote(), timeout=60)  # warm a worker
    dt, _ = timed(lambda: ray_tpu.get([nop.remote() for _ in range(n)],
                                      timeout=300))
    return n / dt


def bench_task_roundtrip_us(ray_tpu, n):
    @ray_tpu.remote(num_cpus=0)
    def nop():
        return 0

    ray_tpu.get(nop.remote(), timeout=60)
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote(), timeout=60)
    return (time.perf_counter() - t0) / n * 1e6


def _actor(ray_tpu):
    @ray_tpu.remote(num_cpus=0)
    class A:
        def m(self, x=0):
            return x

    return A.remote()


def bench_actor_calls_sync_per_s(ray_tpu, n):
    a = _actor(ray_tpu)
    ray_tpu.get(a.m.remote(), timeout=60)
    dt, _ = timed(lambda: [ray_tpu.get(a.m.remote(), timeout=60)
                           for _ in range(n)])
    return n / dt


def bench_actor_calls_async_per_s(ray_tpu, n):
    a = _actor(ray_tpu)
    ray_tpu.get(a.m.remote(), timeout=60)
    dt, _ = timed(lambda: ray_tpu.get([a.m.remote(i) for i in range(n)],
                                      timeout=300))
    return n / dt


def bench_put_small_per_s(ray_tpu, n):
    payload = b"x" * 1024
    dt, _ = timed(lambda: [ray_tpu.put(payload) for _ in range(n)])
    return n / dt


def bench_put_get_large_gbps(ray_tpu, n_mb=64, chunk_mb=16):
    arr = np.random.default_rng(0).integers(
        0, 255, size=chunk_mb << 20, dtype=np.uint8)
    refs = []
    reps = max(1, n_mb // chunk_mb)
    t0 = time.perf_counter()
    for _ in range(reps):
        refs.append(ray_tpu.put(arr))
    put_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = ray_tpu.get(refs, timeout=300)
    # Touch the bytes: a page-strided checksum forces every page of the
    # zero-copy shm mapping to actually fault in, so the metric measures
    # data delivery, not mmap registration speed (r4 verdict weak #3).
    sums = [int(o[::4096].sum()) for o in outs]
    get_dt = time.perf_counter() - t0
    expected = int(arr[::4096].sum())
    assert all(o.nbytes == arr.nbytes for o in outs)
    assert all(s == expected for s in sums), "corrupt bytes from get()"
    total_gb = reps * arr.nbytes / 1e9
    return total_gb / put_dt, total_gb / get_dt


def bench_wait_fanin_s(ray_tpu, n):
    @ray_tpu.remote(num_cpus=0)
    def val(i):
        return i

    refs = [val.remote(i) for i in range(n)]
    t0 = time.perf_counter()
    ready, not_ready = ray_tpu.wait(refs, num_returns=n, timeout=300)
    dt = time.perf_counter() - t0
    assert len(ready) == n
    return dt


def bench_dag_hop(ray_tpu, n):
    """Compiled-DAG hop latency vs the equivalent actor-call round-trip."""
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0)
    class Ident:
        def f(self, x):
            return x

    rpc_actor = Ident.remote()
    dag_actor = Ident.remote()
    with InputNode() as x:
        dag = dag_actor.f.bind(x)
    compiled = dag.experimental_compile()
    try:
        ray_tpu.get(compiled.execute(0), timeout=60)
        ray_tpu.get(rpc_actor.f.remote(0), timeout=60)
        t0 = time.perf_counter()
        for i in range(n):
            ray_tpu.get(compiled.execute(i), timeout=60)
        dag_us = (time.perf_counter() - t0) / n * 1e6
        t0 = time.perf_counter()
        for i in range(n):
            ray_tpu.get(rpc_actor.f.remote(i), timeout=60)
        rpc_us = (time.perf_counter() - t0) / n * 1e6
        return dag_us, rpc_us
    finally:
        compiled.teardown()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--round", type=int, default=3)
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    # The GCS and node manager run as SEPARATE processes (the deployed
    # topology): an in-process cluster shares the driver's GIL and
    # understates task throughput ~3x.
    import os
    import subprocess
    import sys

    os.environ.setdefault("RAY_TPU_DISABLE_AGENT", "1")
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.abspath(__file__))] + sys.path))

    def _read_port(proc, tag):
        while True:
            line = proc.stdout.readline().strip()
            if line.startswith(f"{tag}="):
                return int(line.split("=", 1)[1])
            if not line and proc.poll() is not None:
                raise RuntimeError(f"failed to start ({tag})")

    gcs_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs.server", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    address = f"127.0.0.1:{_read_port(gcs_proc, 'GCS_PORT')}"
    nm_proc = None
    try:
        nm_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_manager.server",
             "--gcs-address", address, "--num-cpus", "4",
             "--num-tpus", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            text=True)
        _read_port(nm_proc, "NODE_PORT")
    except BaseException:
        if nm_proc is not None:
            nm_proc.terminate()
        gcs_proc.terminate()
        raise

    import ray_tpu

    try:
        _run_benchmarks(ray_tpu, address, args)
    finally:
        nm_proc.terminate()
        gcs_proc.terminate()
        nm_proc.wait(timeout=10)
        gcs_proc.wait(timeout=10)


def _run_benchmarks(ray_tpu, address, args):
    ray_tpu.init(address=address)

    scale = 0.2 if args.quick else 1.0
    n_tasks = int(500 * scale)
    n_calls = int(500 * scale)
    n_wait = int(1000 * scale)

    import sys as _sys

    def _stage(name):
        print(f"[bench stage] {name}", file=_sys.stderr, flush=True)

    metrics = {}
    _stage("warmup")
    # Steady-state measurement (reference ray_perf.py warms before timing):
    # the first fan-out pays worker-pool spawns, not task-path costs.
    bench_tasks_per_s(ray_tpu, max(100, n_tasks // 2))
    _stage("tasks_per_s")
    metrics["tasks_per_s"] = round(bench_tasks_per_s(ray_tpu, n_tasks), 1)
    _stage("task_roundtrip_us")
    metrics["task_roundtrip_us"] = round(
        bench_task_roundtrip_us(ray_tpu, max(50, n_tasks // 5)), 1)
    _stage("actor_calls_sync")
    metrics["actor_calls_sync_per_s"] = round(
        bench_actor_calls_sync_per_s(ray_tpu, n_calls), 1)
    _stage("actor_calls_async")
    metrics["actor_calls_async_per_s"] = round(
        bench_actor_calls_async_per_s(ray_tpu, n_calls), 1)
    _stage("put_1kb")
    metrics["put_1kb_per_s"] = round(
        bench_put_small_per_s(ray_tpu, int(2000 * scale)), 1)
    _stage("put_get_large")
    put_gbps, get_gbps = bench_put_get_large_gbps(
        ray_tpu, n_mb=int(64 * scale) or 16)
    metrics["put_large_gb_per_s"] = round(put_gbps, 3)
    metrics["get_large_gb_per_s"] = round(get_gbps, 3)
    _stage("wait_fanin")
    metrics["wait_1k_fanin_s"] = round(bench_wait_fanin_s(ray_tpu, n_wait), 3)
    _stage("dag_hop")
    dag_us, rpc_us = bench_dag_hop(ray_tpu, max(100, int(200 * scale)))
    metrics["compiled_dag_hop_us"] = round(dag_us, 1)
    metrics["actor_call_roundtrip_us"] = round(rpc_us, 1)
    metrics["dag_vs_rpc_speedup"] = round(rpc_us / dag_us, 2)

    ray_tpu.shutdown()

    for k, v in metrics.items():
        print(json.dumps({"metric": k, "value": v}))
    out = f"BENCH_CORE_r{args.round:02d}.json"
    with open(out, "w") as f:
        json.dump({"metrics": metrics, "ts": time.time()}, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
