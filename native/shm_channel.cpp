// Mutable shared-memory channel for compiled DAGs.
//
// Reference analog: the mutable-object channel of accelerated DAGs
// (src/ray/core_worker/experimental_mutable_object_manager.h and
// python/ray/experimental/channel/shared_memory_channel.py:151): one
// fixed-capacity buffer a writer mutates in place and N readers consume,
// synchronized without RPCs so a DAG hop costs microseconds, not a
// lease/submit round-trip.
//
// Protocol (single writer, up to MAX_READERS readers, seqlock-style):
//   * `version` is even when the buffer is stable, odd while the writer
//     mutates it. Stable versions advance 0 -> 2 -> 4 ...
//   * a reader waits for an even version newer than the one it last
//     consumed, copies the payload, re-checks the version (seqlock
//     validate), then stores the version in its ack slot.
//   * the writer waits until every ack slot equals the current version
//     before mutating, so a payload is never overwritten while a reader
//     still owes a read. This is the in-place analog of the reference's
//     WriteAcquire/ReadRelease cycle.
//   * close() publishes a sticky closed flag; readers drain any pending
//     value first, then observe it; blocked writers abort with it.
//
// Waits spin briefly then back off to nanosleep, releasing the GIL the
// whole time (callers come through ctypes).

#include <atomic>
#include <cerrno>
#include <climits>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMaxReaders = 16;
constexpr uint32_t kMagic = 0x52544348;  // "RTCH"

struct alignas(64) Header {
  uint32_t magic;
  uint32_t n_readers;
  uint64_t capacity;
  std::atomic<uint64_t> version;
  std::atomic<uint64_t> size;
  std::atomic<uint64_t> closed;  // set once; never clobbers a pending value
  // Cross-process wake word: bumped + FUTEX_WAKEd after every state
  // change. nanosleep-based backoff had a ~50us floor (default kernel
  // timer slack), which put a 100-200us tax on every DAG hop; futex
  // wakes land in single-digit microseconds.
  std::atomic<uint32_t> futex_word;
  alignas(64) std::atomic<uint64_t> acks[kMaxReaders];
};
static_assert(offsetof(Header, acks) == 64, "python fallback expects acks@64");
static_assert(sizeof(Header) == 192, "python fallback expects data@192");

struct Handle {
  Header* hdr;
  char* data;
  size_t map_bytes;
  int reader_idx;          // -1 for the writer
  uint64_t last_seen;      // reader: last consumed version
  char name[256];
};

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

void futex_bump_wake(Header* hdr) {
  hdr->futex_word.fetch_add(1, std::memory_order_release);
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(&hdr->futex_word),
          FUTEX_WAKE, INT_MAX, nullptr, nullptr, 0);
}

// Spin briefly (the ping-pong fast path), then futex-wait on the shared
// wake word. The wait is bounded (50ms) as defense in depth against a
// peer that mutates state without waking (e.g. a crashed process's
// partially-applied write). Returns false on timeout (timeout_s < 0 means
// wait forever).
template <typename Pred>
bool wait_until(Header* hdr, Pred pred, double timeout_s) {
  for (int i = 0; i < 2000; ++i) {
    if (pred()) return true;
  }
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  while (true) {
    uint32_t seq = hdr->futex_word.load(std::memory_order_acquire);
    if (pred()) return true;
    double remain = 0.05;
    if (deadline > 0) {
      remain = deadline - now_s();
      if (remain <= 0) return pred();
      if (remain > 0.05) remain = 0.05;
    }
    struct timespec ts;
    ts.tv_sec = (time_t)remain;
    ts.tv_nsec = (long)((remain - (double)ts.tv_sec) * 1e9);
    syscall(SYS_futex, reinterpret_cast<uint32_t*>(&hdr->futex_word),
            FUTEX_WAIT, seq, &ts, nullptr, 0);
  }
}

Handle* map_channel(const char* name, uint64_t capacity, bool create,
                    uint32_t n_readers, int reader_idx) {
  size_t bytes = sizeof(Header) + capacity;
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (create && ftruncate(fd, (off_t)bytes) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!create) {
    // Capacity comes from the header for attachers.
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    bytes = st.st_size;
  }
  void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = static_cast<Header*>(mem);
  if (create) {
    std::memset(mem, 0, sizeof(Header));
    hdr->capacity = capacity;
    hdr->n_readers = n_readers;
    hdr->magic = kMagic;  // last: attachers poll for it
  } else if (hdr->magic != kMagic) {
    munmap(mem, bytes);
    return nullptr;
  }
  Handle* h = new Handle();
  h->hdr = hdr;
  h->data = static_cast<char*>(mem) + sizeof(Header);
  h->map_bytes = bytes;
  h->reader_idx = reader_idx;
  h->last_seen = 0;
  std::snprintf(h->name, sizeof(h->name), "%s", name);
  return h;
}

}  // namespace

extern "C" {

// Writer-side create. Returns NULL on failure.
void* chan_create(const char* name, uint64_t capacity, uint32_t n_readers) {
  if (n_readers == 0 || n_readers > kMaxReaders) return nullptr;
  return map_channel(name, capacity, /*create=*/true, n_readers, -1);
}

// Attach an existing channel; reader_idx in [0, n_readers) for readers,
// -1 to attach as (take over) the writer.
void* chan_attach(const char* name, int reader_idx) {
  return map_channel(name, 0, /*create=*/false, 0, reader_idx);
}

uint64_t chan_capacity(void* handle) {
  return static_cast<Handle*>(handle)->hdr->capacity;
}

// 0 ok, -1 timeout, -2 payload too large, -3 channel closed.
int chan_write(void* handle, const char* buf, uint64_t len, double timeout_s) {
  Handle* h = static_cast<Handle*>(handle);
  Header* hdr = h->hdr;
  if (len > hdr->capacity) return -2;
  if (hdr->closed.load(std::memory_order_acquire)) return -3;
  uint64_t v = hdr->version.load(std::memory_order_relaxed);
  uint32_t n = hdr->n_readers;
  auto all_acked = [&] {
    if (hdr->closed.load(std::memory_order_acquire)) return true;  // abort
    for (uint32_t i = 0; i < n; ++i) {
      if (hdr->acks[i].load(std::memory_order_acquire) != v) return false;
    }
    return true;
  };
  if (!wait_until(hdr, all_acked, timeout_s)) return -1;
  if (hdr->closed.load(std::memory_order_acquire)) return -3;
  hdr->version.store(v + 1, std::memory_order_release);  // odd: mutating
  std::memcpy(h->data, buf, len);
  hdr->size.store(len, std::memory_order_release);
  hdr->version.store(v + 2, std::memory_order_release);  // even: stable
  futex_bump_wake(hdr);
  return 0;
}

// >=0: payload size copied into out, -1 timeout, -3 closed, -4 out_cap too
// small (payload left unconsumed).
int64_t chan_read(void* handle, char* out, uint64_t out_cap, double timeout_s) {
  Handle* h = static_cast<Handle*>(handle);
  Header* hdr = h->hdr;
  auto fresh = [&] {
    uint64_t v = hdr->version.load(std::memory_order_acquire);
    return (v % 2 == 0 && v != h->last_seen) ||
           hdr->closed.load(std::memory_order_acquire);
  };
  if (!wait_until(hdr, fresh, timeout_s)) return -1;
  while (true) {
    uint64_t v = hdr->version.load(std::memory_order_acquire);
    if (v % 2 != 0) continue;  // writer mid-mutation; stable soon
    if (v == h->last_seen) {
      // No unconsumed value; closed means no more will ever arrive.
      if (hdr->closed.load(std::memory_order_acquire)) return -3;
      continue;
    }
    uint64_t len = hdr->size.load(std::memory_order_acquire);
    if (len > out_cap) return -4;
    std::memcpy(out, h->data, len);
    // Seqlock validate: a torn copy shows as a version change.
    if (hdr->version.load(std::memory_order_acquire) == v) {
      h->last_seen = v;
      if (h->reader_idx >= 0) {
        hdr->acks[h->reader_idx].store(v, std::memory_order_release);
        futex_bump_wake(hdr);  // unblock a writer waiting on acks
      }
      return (int64_t)len;
    }
  }
}

// Publish the closed flag. A value written before close is still readable;
// reads past it return -3.
void chan_close(void* handle) {
  Header* hdr = static_cast<Handle*>(handle)->hdr;
  hdr->closed.store(1, std::memory_order_release);
  futex_bump_wake(hdr);
}

void chan_detach(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->hdr, h->map_bytes);
  delete h;
}

void chan_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
