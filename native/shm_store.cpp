// ray_tpu shared-memory object store (plasma-equivalent).
//
// Reference: src/ray/object_manager/plasma (SURVEY.md C12) — an immutable
// node-local object store in shared memory with LRU eviction. TPU-native
// re-design: instead of one mmap'd arena + dlmalloc + fd-passing over a unix
// socket, every object is its own POSIX shm segment (shm_open + mmap).
// Readers in any process map segments directly (zero-copy data plane); the
// control plane (who-has-what) stays in the node manager's gRPC service.
// POSIX keeps a mapping alive after shm_unlink, which gives plasma's
// "eviction never invalidates live readers" property for free.
//
// Exposed as a C API for ctypes (the reference's client is C++ linked via
// Cython; here the binding layer is ctypes per the build constraints).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <list>
#include <mutex>
#include <string>
#include <unordered_map>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Entry {
  std::string name;       // shm segment name (includes leading '/')
  uint64_t size = 0;
  std::list<std::string>::iterator lru_it;  // position in lru list
};

struct Store {
  std::string prefix;
  uint64_t capacity = 0;
  uint64_t used = 0;
  std::mutex mu;
  std::unordered_map<std::string, Entry> index;  // object id (hex) -> entry
  std::list<std::string> lru;                    // front = most recent
  // Objects mid-write (capacity reserved, segment not yet sealed). Kept out
  // of `index` so readers can never map a partially written segment.
  std::unordered_map<std::string, uint64_t> pending;
};

std::string SegmentName(const Store* s, const std::string& oid) {
  // shm names are limited to NAME_MAX-4; oid hex (56 chars) + prefix fits.
  return "/" + s->prefix + "." + oid;
}

// Unlink + drop one entry (store lock must be held).
void DropLocked(Store* s, std::unordered_map<std::string, Entry>::iterator it) {
  shm_unlink(it->second.name.c_str());
  s->used -= it->second.size;
  s->lru.erase(it->second.lru_it);
  s->index.erase(it);
}

// Evict least-recently-used entries until `need` bytes fit (lock held).
bool EvictLocked(Store* s, uint64_t need) {
  while (s->used + need > s->capacity && !s->lru.empty()) {
    const std::string victim = s->lru.back();
    auto it = s->index.find(victim);
    if (it == s->index.end()) {
      s->lru.pop_back();
      continue;
    }
    DropLocked(s, it);
  }
  return s->used + need <= s->capacity;
}

}  // namespace

extern "C" {

// Create a store handle. `prefix` scopes segment names per node; `capacity`
// bounds total bytes before LRU eviction kicks in.
void* shm_store_create(const char* prefix, uint64_t capacity) {
  auto* s = new Store();
  s->prefix = prefix;
  s->capacity = capacity;
  return s;
}

void shm_store_destroy(void* handle) {
  auto* s = static_cast<Store*>(handle);
  {
    // The guard must release before delete: unlocking a mutex inside the
    // freed Store is a use-after-free (found by the TSAN stress target).
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->index) {
      shm_unlink(kv.second.name.c_str());
    }
  }
  delete s;
}

// Create + fill + seal an object. Returns 0 on success, -1 on failure,
// -2 if it cannot fit even after eviction. Writes the segment name into
// name_out (cap name_cap).
int shm_store_put(void* handle, const char* oid, const void* data,
                  uint64_t size, char* name_out, uint64_t name_cap) {
  auto* s = static_cast<Store*>(handle);
  std::string name;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->index.count(oid)) {  // immutable: re-put is a no-op
      const Entry& e = s->index[oid];
      snprintf(name_out, name_cap, "%s", e.name.c_str());
      return 0;
    }
    name = SegmentName(s, oid);
    if (s->pending.count(oid)) {
      // Another thread is writing the same immutable object; report its
      // name — readers stay safe because lookups miss until it seals.
      snprintf(name_out, name_cap, "%s", name.c_str());
      return 0;
    }
    if (!EvictLocked(s, size)) return -2;
    s->used += size;  // reserve before the copy so parallel puts respect cap
    s->pending.emplace(oid, size);
  }
  // Create + fill OUTSIDE the index: a concurrent Get must never hand a
  // reader the name of a segment that isn't fully written yet (mapping
  // past a short file's end SIGBUSes the reader). Plasma's Create/Seal
  // boundary, collapsed to "insert into the index only once sealed".
  int fd = shm_open(name.c_str(), O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0 && errno == EEXIST) {
    shm_unlink(name.c_str());  // stale segment from a crashed predecessor
    fd = shm_open(name.c_str(), O_CREAT | O_RDWR | O_EXCL, 0600);
  }
  bool ok = fd >= 0 && ftruncate(fd, (off_t)size) == 0;
  if (ok && size > 0) {
    void* dst = mmap(nullptr, size, PROT_WRITE, MAP_SHARED, fd, 0);
    ok = dst != MAP_FAILED;
    if (ok) {
      memcpy(dst, data, size);
      munmap(dst, size);
    }
  }
  if (fd >= 0) close(fd);
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->pending.erase(oid);
    if (!ok) {
      s->used -= size;
      shm_unlink(name.c_str());
      return -1;
    }
    s->lru.push_front(oid);
    Entry e{name, size, s->lru.begin()};
    s->index.emplace(oid, e);
  }
  snprintf(name_out, name_cap, "%s", name.c_str());
  return 0;
}

// Register an object some *other* process already created+sealed (worker-side
// zero-copy put: the worker wrote the segment, the store only indexes it).
int shm_store_register(void* handle, const char* oid, const char* name,
                       uint64_t size) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->index.count(oid)) return 0;
  if (!EvictLocked(s, size)) return -2;
  s->used += size;
  s->lru.push_front(oid);
  Entry e{name, size, s->lru.begin()};
  s->index.emplace(oid, e);
  return 0;
}

// Look up an object. Returns 0 and fills name_out/size_out, or -1 if absent.
// Touches the LRU position.
int shm_store_get(void* handle, const char* oid, char* name_out,
                  uint64_t name_cap, uint64_t* size_out) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(oid);
  if (it == s->index.end()) return -1;
  s->lru.erase(it->second.lru_it);
  s->lru.push_front(it->first);
  it->second.lru_it = s->lru.begin();
  snprintf(name_out, name_cap, "%s", it->second.name.c_str());
  *size_out = it->second.size;
  return 0;
}

// Object id of the least-recently-used entry (spill victim selection).
// Returns 0 and fills oid_out, or -1 when the store is empty.
int shm_store_coldest(void* handle, char* oid_out, uint64_t oid_cap) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->lru.empty()) return -1;
  snprintf(oid_out, oid_cap, "%s", s->lru.back().c_str());
  return 0;
}

int shm_store_contains(void* handle, const char* oid) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->index.count(oid) ? 1 : 0;
}

int shm_store_delete(void* handle, const char* oid) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(oid);
  if (it == s->index.end()) return -1;
  DropLocked(s, it);
  return 0;
}

uint64_t shm_store_used(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->used;
}

uint64_t shm_store_count(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->index.size();
}

// ---------------------------------------------------------------- client API
// Map an existing sealed segment read-only. Returns pointer or NULL.
void* shm_client_map(const char* name, uint64_t size) {
  int fd = shm_open(name, O_RDONLY, 0);
  if (fd < 0) return nullptr;
  // Mapping past a short file SIGBUSes on access; a not-fully-written
  // segment (e.g. a concurrent creator between create and seal) must read
  // as "not available yet", not crash the reader.
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < size) {
    close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, size, PROT_READ, MAP_SHARED, fd, 0);
  close(fd);
  return p == MAP_FAILED ? nullptr : p;
}

void shm_client_unmap(void* ptr, uint64_t size) {
  if (ptr) munmap(ptr, size);
}

// Worker-side create+write+seal in one call (the client writes the data
// plane itself; only metadata goes to the store — reference: plasma clients
// Create/Seal over shared memory, store.h:55).
// Drop a client-created segment that was never registered with a store
// (e.g. the object was freed before its put flush landed).
int shm_client_unlink(const char* name) { return shm_unlink(name); }

int shm_client_create(const char* name, const void* data, uint64_t size) {
  int fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0 && errno == EEXIST) {
    return 0;  // immutable objects: existing segment is the same content
  }
  if (fd < 0) return -1;
  bool ok = ftruncate(fd, (off_t)size) == 0;
  if (ok && size > 0) {
    void* dst = mmap(nullptr, size, PROT_WRITE, MAP_SHARED, fd, 0);
    ok = dst != MAP_FAILED;
    if (ok) {
      memcpy(dst, data, size);
      munmap(dst, size);
    }
  }
  close(fd);
  if (!ok) {
    shm_unlink(name);
    return -1;
  }
  return 0;
}

}  // extern "C"
