// Multi-threaded stress test for the shared-memory object store.
//
// Race/sanitizer strategy (SURVEY.md §5: the reference leans on absl
// thread-annotations + CI TSAN/ASAN bazel configs): this binary hammers
// every C-API entry point from concurrent threads and is built with
// -fsanitize=thread / address by the Makefile's `tsan` / `asan` targets
// (driven by tests/test_sanitizers.py). Exit code 0 = no crashes and all
// invariants held; sanitizer findings abort the process.

#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// Portable TSAN detection: GCC defines __SANITIZE_THREAD__, Clang exposes
// it via __has_feature(thread_sanitizer).
#if defined(__SANITIZE_THREAD__)
#define RT_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define RT_TSAN 1
#endif
#endif

extern "C" {
void* chan_create(const char* name, uint64_t capacity, uint32_t n_readers);
void* chan_attach(const char* name, int reader_idx);
int chan_write(void* handle, const char* buf, uint64_t len,
               double timeout_s);
int64_t chan_read(void* handle, char* out, uint64_t out_cap,
                  double timeout_s);
void chan_close(void* handle);
void chan_detach(void* handle);
void chan_unlink(const char* name);
void* shm_store_create(const char* prefix, uint64_t capacity);
void shm_store_destroy(void* handle);
int shm_store_put(void* handle, const char* oid, const void* data,
                  uint64_t size, char* name_out, uint64_t name_cap);
int shm_store_get(void* handle, const char* oid, char* name_out,
                  uint64_t name_cap, uint64_t* size_out);
int shm_store_contains(void* handle, const char* oid);
int shm_store_delete(void* handle, const char* oid);
int shm_store_coldest(void* handle, char* oid_out, uint64_t oid_cap);
uint64_t shm_store_used(void* handle);
uint64_t shm_store_count(void* handle);
void* shm_client_map(const char* name, uint64_t size);
void shm_client_unmap(void* ptr, uint64_t size);
}

namespace {

std::atomic<uint64_t> g_errors{0};
std::atomic<uint64_t> g_ops{0};

void worker(void* store, int tid, int iters) {
  std::vector<char> payload(4096 + tid * 64);
  for (size_t i = 0; i < payload.size(); ++i)
    payload[i] = static_cast<char>((tid + i) & 0xff);
  char name[256];
  char coldest[256];
  uint64_t size = 0;
  for (int i = 0; i < iters; ++i) {
    std::string oid = "obj-" + std::to_string(tid) + "-" +
                      std::to_string(i % 32);
    int rc = shm_store_put(store, oid.c_str(), payload.data(),
                           payload.size(), name, sizeof(name));
    g_ops.fetch_add(1, std::memory_order_relaxed);
    if (rc == 0) {
      // Readers may map the segment while other threads churn the store.
      if (shm_store_get(store, oid.c_str(), name, sizeof(name), &size) ==
          0) {
        if (size != payload.size()) {
          g_errors.fetch_add(1);
        } else {
          void* p = shm_client_map(name, size);
          if (p != nullptr) {
            if (std::memcmp(p, payload.data(), 64) != 0)
              g_errors.fetch_add(1);
            shm_client_unmap(p, size);
          }
        }
      }
    }
    if (i % 7 == 0) shm_store_contains(store, oid.c_str());
    if (i % 11 == 0) shm_store_delete(store, oid.c_str());
    if (i % 13 == 0) shm_store_coldest(store, coldest, sizeof(coldest));
    if (i % 17 == 0) {
      shm_store_used(store);
      shm_store_count(store);
    }
  }
}

#ifndef RT_TSAN
// Mutable-channel stress (compiled-DAG data plane, shm_channel.cpp):
// 1 writer + N readers pump checksummed payloads through the seqlock
// protocol. Excluded under TSAN: the reader's pre-validation copy of the
// payload is an *intentional* racy read that the version re-check
// discards when torn (classic seqlock) — TSAN cannot see the validation
// and reports it as a data race. ASAN/UBSAN + the plain build cover the
// channel's memory safety; the store section above runs everywhere.
int channel_stress(int readers, int rounds) {
  std::string name = "/stresschan" + std::to_string(getpid());
  chan_unlink(name.c_str());
  void* w = chan_create(name.c_str(), 1 << 16, readers);
  if (w == nullptr) return 2;
  std::atomic<int> bad{0};
  std::vector<std::thread> pool;
  for (int r = 0; r < readers; ++r) {
    pool.emplace_back([&, r] {
      void* h = chan_attach(name.c_str(), r);
      if (h == nullptr) {
        bad.fetch_add(1);
        return;
      }
      std::vector<char> buf(1 << 16);
      while (true) {
        int64_t n = chan_read(h, buf.data(), buf.size(), 10.0);
        if (n == -3) break;          // closed
        if (n < 0) {
          bad.fetch_add(1);
          break;
        }
        unsigned sum = 0;
        for (int64_t i = 1; i < n; ++i)
          sum += static_cast<unsigned char>(buf[i]);
        if (static_cast<unsigned char>(buf[0]) !=
            static_cast<unsigned char>(sum & 0xff))
          bad.fetch_add(1);
      }
      chan_detach(h);
    });
  }
  std::vector<char> payload(1 << 12);
  for (int i = 0; i < rounds; ++i) {
    for (size_t j = 1; j < payload.size(); ++j)
      payload[j] = static_cast<char>((i * 31 + j) & 0xff);
    unsigned sum = 0;
    for (size_t j = 1; j < payload.size(); ++j)
      sum += static_cast<unsigned char>(payload[j]);
    payload[0] = static_cast<char>(sum & 0xff);
    if (chan_write(w, payload.data(), payload.size(), 10.0) != 0) {
      bad.fetch_add(1);
      break;
    }
  }
  chan_close(w);
  for (auto& th : pool) th.join();
  chan_detach(w);
  chan_unlink(name.c_str());
  return bad.load() == 0 ? 0 : 1;
}
#endif  // !RT_TSAN

}  // namespace

int main(int argc, char** argv) {
  int threads = argc > 1 ? std::atoi(argv[1]) : 8;
  int iters = argc > 2 ? std::atoi(argv[2]) : 2000;
  // Capacity below the ~1.1MB peak working set so EvictLocked churns
  // under concurrency (eviction racing shm_client_map is the hot race).
  std::string prefix = "stress" + std::to_string(getpid());
  void* store = shm_store_create(prefix.c_str(), 1 << 19);
  if (store == nullptr) {
    std::fprintf(stderr, "store create failed\n");
    return 2;
  }
  std::vector<std::thread> pool;
  for (int t = 0; t < threads; ++t)
    pool.emplace_back(worker, store, t, iters);
  for (auto& th : pool) th.join();
  uint64_t errors = g_errors.load();
  std::printf("ops=%llu errors=%llu used=%llu count=%llu\n",
              static_cast<unsigned long long>(g_ops.load()),
              static_cast<unsigned long long>(errors),
              static_cast<unsigned long long>(shm_store_used(store)),
              static_cast<unsigned long long>(shm_store_count(store)));
  shm_store_destroy(store);
  if (errors != 0) return 1;
#ifndef RT_TSAN
  int rc = channel_stress(/*readers=*/3, /*rounds=*/1000);
  if (rc != 0) {
    std::fprintf(stderr, "channel stress failed rc=%d\n", rc);
    return rc;
  }
  std::printf("CHANNEL OK\n");
#endif
  std::printf("STRESS OK\n");
  return 0;
}
