"""Serving benchmark: continuous-batching decode throughput on one chip.

Prints ONE JSON line and writes ``BENCH_SERVE_r{N}.json``.

Metric: steady-state decode tokens/sec/chip of the ContinuousBatcher
(``models/continuous_batching.py``) running the same ~1B-param Llama the
training bench uses, all KV slots saturated.

Criterion (v5e HBM roofline): every decode tick must read the full
parameter set plus the active KV prefixes from HBM, so
``roofline_tokens_per_s = num_slots * HBM_BW / (param_bytes + kv_bytes)``.
The criterion is 10% of this roofline: XLA (non-pallas) decode with
per-slot cache scatter plus a REMOTE-attached chip (every host fetch
costs a ~90ms tunnel RTT; the engine's speculative buffered decode hides
most but not all of it) lands 10-15%; vLLM-class stacks on local GPUs
land ~15-30%. ``vs_baseline`` = achieved / (0.10 * roofline), and
``hbm_efficiency`` reports the raw fraction transparently.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

HBM_GBPS = {
    "TPU v5 lite": 819e9,   # v5e
    "TPU v5": 2765e9,       # v5p
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,  # v6e
}


def _hbm_bw(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, bw in HBM_GBPS.items():
        if kind.startswith(name):
            return bw
    return 819e9


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.models.continuous_batching import ContinuousBatcher

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        config = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16, head_dim=128,
            max_seq_len=2048)
        num_slots, max_len, prompt_len, ticks = 32, 512, 32, 120
        sync_every = 32  # remote-attached chip: ~90ms per host fetch
    else:  # CI fallback: always emit a line
        config = llama.LlamaConfig.tiny()
        num_slots, max_len, prompt_len, ticks = 4, 64, 8, 20
        sync_every = 4

    eng = ContinuousBatcher(config, num_slots=num_slots, max_len=max_len,
                            sync_every=sync_every)
    param_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(eng.params))

    def top_up():
        while len(eng._slots) + len(eng._waiting) < num_slots:
            eng.submit(list(range(1, prompt_len + 1)),
                       max_new_tokens=max_len - prompt_len - 1)

    # Warm: compile prefill + tick, reach steady state.
    top_up()
    for _ in range(5):
        eng.step()
        top_up()

    # Timed region at full occupancy. No per-tick device sync: the
    # buffered engine's whole point is overlapping fetches with compute,
    # so the wall clock over the window is the honest measure.
    t0 = time.perf_counter()
    for _ in range(ticks):
        top_up()
        eng.step()
    jax.block_until_ready(eng.cache.k)
    wall = time.perf_counter() - t0
    med = wall / ticks
    tokens_per_s = num_slots / med

    # Roofline: params + average live KV prefix, read once per tick.
    avg_pos = (prompt_len + max_len) / 2
    kv_bytes = (num_slots * avg_pos * config.num_layers
                * 2 * config.num_kv_heads * config.head_dim * 2)
    bw = _hbm_bw(jax.devices()[0])
    roofline = num_slots * bw / (param_bytes + kv_bytes)
    criterion = 0.10 * roofline

    out = {
        "metric": "decode_tokens_per_s_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_s / criterion, 3),
        "roofline_tokens_per_s": round(roofline, 1),
        "hbm_efficiency": round(tokens_per_s / roofline, 3),
        "mean_tick_ms": round(med * 1e3, 2),
        "num_slots": num_slots,
        "sync_every": sync_every,
        "param_bytes": param_bytes,
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "on_tpu": on_tpu,
    }
    print(json.dumps(out))
    rnd = int(sys.argv[sys.argv.index("--round") + 1]) \
        if "--round" in sys.argv else 5
    with open(f"BENCH_SERVE_r{rnd:02d}.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
