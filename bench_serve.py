"""Serving benchmark: continuous-batching decode throughput on one chip.

Prints ONE JSON line and writes ``BENCH_SERVE_r{N}.json``.

Metric: steady-state decode tokens/sec/chip of the ContinuousBatcher
(``models/continuous_batching.py``) running the same ~1B-param Llama the
training bench uses, all KV slots saturated — PAGED KV arena by default
(block tables + optional int8 storage), which is the ISSUE-6 roofline
lever. Also reported: time-to-first-token (submit -> first streamed
token, p50/p95 over every request admitted during the run), prefill
tokens/s, and TWO per-tick bytes-read figures so regressions are
attributable:

* ``bytes_read_per_tick_cost`` — the compiled tick's ``cost_analysis()``
  harvested by the XLA monitor (static: prices the paged program at its
  worst case, every table entry live);
* ``bytes_read_per_tick_live`` — the engine's live-token accounting
  (params + live KV blocks actually streamed), which is what the
  achieved-bandwidth gauges use and what must SCALE WITH LIVE TOKENS
  rather than ``S_max``.

A ``sweep`` section measures decode tokens/s and both byte figures
across ``kv_dtype x block_size`` so the r06 entry captures the roofline
climb curve, not one point. A ``spec_phase`` section (r06+) runs the
speculative-decoding ladder — committed decode tokens/s at ``spec_k``
in {0, 2, 4} with accept rates — since a spec tick commits a variable
number of tokens, all throughput figures here are COMMITTED tokens
over wall time, never ticks times slots. A ``disagg_phase`` section
(r07+) A/Bs colocated against split prefill/decode engines on a mixed
long-prefill/long-decode backlog — TTFT/TPOT each way, KV-transfer
bytes/s over the real shm-channel path, and the export/channel/import
handoff breakdown, which must sum to the measured handoff wall.

Criterion (v5e HBM roofline): every decode tick must read the full
parameter set plus the active KV prefixes from HBM, so
``roofline_tokens_per_s = num_slots * HBM_BW / (param_bytes + kv_bytes)``
with ``kv_bytes`` priced at the ENGINE'S OWN storage (bf16 dense, or the
paged arena's bf16/int8 bytes-per-token). The criterion is 10% of the
bf16-dense roofline: XLA (non-pallas) decode with per-slot cache scatter
plus a REMOTE-attached chip lands 10-15%; the dense fused kernel
targeted >=25%; the paged kernel removes the padding traffic entirely
(a slot reads its live blocks, not ``S_max``) and int8 halves the rest,
targeting >=3x the r05 tokens/s. ``vs_baseline`` = achieved /
(0.10 * roofline), and ``hbm_efficiency`` reports the raw fraction.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

HBM_GBPS = {
    "TPU v5 lite": 819e9,   # v5e
    "TPU v5": 2765e9,       # v5p
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,  # v6e
}


def _hbm_bw(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, bw in HBM_GBPS.items():
        if kind.startswith(name):
            return bw
    return 819e9


def _pct(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _tick_cost_stats() -> tuple:
    """The compiled cb_tick's cost-analysis (bytes, flops) for the
    latest compile — zeros when the backend offers no cost analysis."""
    from ray_tpu._private import xla_monitor

    stats = xla_monitor.program_stats("cb_tick") or {}
    return (int(stats.get("bytes_accessed") or 0),
            int(stats.get("flops") or 0))


def _breakdown_pcts(breakdowns) -> dict:
    """p50/p95 of the TTFT decomposition from engine request records."""
    churn = [b for b in breakdowns
             if b["outcome"] == "finished" and b["ttft_s"] is not None]
    out = {}
    for comp in ("queue", "arena_wait", "prefill", "ttft", "tpot"):
        vals = sorted(b[f"{comp}_s"] for b in churn
                      if b.get(f"{comp}_s") is not None)
        out[f"{comp}_p50_ms"] = round(_pct(vals, 0.50) * 1e3, 2)
        out[f"{comp}_p95_ms"] = round(_pct(vals, 0.95) * 1e3, 2)
    out["samples"] = len(churn)
    return out


def _prefix_phase(config, params, num_slots, max_len, sync_every,
                  block_size, shared_blocks, tail_len, rounds,
                  shared_frac=0.75) -> dict:
    """Prefix-reuse churn: ``shared_frac`` of requests share one system
    prompt (``shared_blocks`` full KV blocks) ahead of a unique tail —
    the chat-fleet traffic shape prefix caching exists for. Runs the
    same schedule with the prefix cache ON and OFF and reports
    ``prefix_hit_rate``, ``prefill_tokens_saved``, effective prefill
    throughput (tokens the clients asked prefilled over the engine's own
    prefill wall time — cached tokens cost ~0), and the
    ``ttft_breakdown`` each way. The routing analog (affinity keeps a
    prefix's requests on the replica holding it) rides the same engine
    counters per replica."""
    import numpy as _np

    from ray_tpu.models.continuous_batching import ContinuousBatcher

    rng = _np.random.default_rng(17)
    shared = list(map(int, rng.integers(1, config.vocab_size,
                                        size=shared_blocks * block_size)))
    sched = []
    for i in range(rounds * num_slots):
        if (i % 4) < int(round(shared_frac * 4)):
            prompt = shared + list(map(int, rng.integers(
                1, config.vocab_size, size=tail_len)))
        else:
            prompt = list(map(int, rng.integers(
                1, config.vocab_size,
                size=shared_blocks * block_size + tail_len)))
        sched.append(prompt)
    out = {"shared_frac": shared_frac,
           "shared_prefix_tokens": len(shared)}
    for on in (True, False):
        eng = ContinuousBatcher(config, params=params,
                                num_slots=num_slots, max_len=max_len,
                                sync_every=sync_every, paged=True,
                                block_size=block_size, prefix_cache=on)
        # Warm-up = the steady state of a serving replica: the system
        # prompt is resident AND both prefill program shapes (cold full
        # prompt, warm suffix-after-match) are compiled before timing.
        for _ in range(2):
            eng.submit(list(sched[0]), max_new_tokens=2)
            while eng.has_work():
                eng.step()
        eng.request_breakdowns.clear()
        hit0, miss0 = eng.prefix_hit_tokens, eng.prefix_miss_tokens
        prefill0, pwall0 = eng.prefill_tokens, eng.prefill_seconds
        t0 = time.perf_counter()
        for prompt in sched:
            eng.submit(list(prompt), max_new_tokens=4)
            eng.step()
        while eng.has_work():
            eng.step()
        wall = time.perf_counter() - t0
        hits = eng.prefix_hit_tokens - hit0
        misses = eng.prefix_miss_tokens - miss0
        prefilled = eng.prefill_tokens - prefill0
        asked = (hits + misses) if on else prefilled
        prefill_wall = max(eng.prefill_seconds - pwall0, 1e-9)
        key = "cache_on" if on else "cache_off"
        out[key] = {
            "prefix_hit_rate": round(hits / max(hits + misses, 1), 4),
            "prefill_tokens": prefilled,
            "prefill_tokens_saved": hits,
            "effective_prefill_tokens_per_s": round(
                asked / prefill_wall, 1),
            "wall_s": round(wall, 3),
            "ttft_breakdown": _breakdown_pcts(eng.request_breakdowns),
        }
    on_d, off_d = out["cache_on"], out["cache_off"]
    out["prefill_tokens_saved_frac"] = round(
        on_d["prefill_tokens_saved"]
        / max(on_d["prefill_tokens_saved"] + on_d["prefill_tokens"], 1),
        4)
    out["effective_prefill_speedup"] = round(
        on_d["effective_prefill_tokens_per_s"]
        / max(off_d["effective_prefill_tokens_per_s"], 1e-9), 3)
    return out


def _measure_decode(eng, num_slots, max_len, prompt_len, ticks):
    """Steady-state decode tokens/s at full occupancy (compile warm-up
    included). Returns (tokens_per_s, mean_tick_s, live_bytes).

    Throughput is COMMITTED tokens over wall time — not slots/tick —
    because a speculative tick commits a variable number of tokens per
    slot. Buffered engines apply tokens at fetch boundaries, so the
    window is flushed (inside the timed interval) before counting."""
    def top_up():
        while len(eng._slots) + len(eng._waiting) < num_slots:
            eng.submit(list(range(1, prompt_len + 1)),
                       max_new_tokens=max_len - prompt_len - 1)
    top_up()
    for _ in range(5):
        eng.step()
        top_up()
    while eng._buf or eng._pending:  # start the window with clean books
        eng.step()
    live_before = eng.tick_bytes_estimate()
    decoded0 = eng.decoded_tokens
    nticks = ticks
    t0 = time.perf_counter()
    for _ in range(ticks):
        top_up()
        eng.step()
    while eng._buf or eng._pending:  # drain the speculative buffer
        eng.step()
        nticks += 1
    jax.block_until_ready(eng.cache.k)
    wall = time.perf_counter() - t0
    med = wall / nticks
    committed = eng.decoded_tokens - decoded0
    # Live positions grow linearly across the window, so the mean of the
    # endpoint estimates IS the window's average per-tick traffic — a
    # single start-of-window snapshot would understate it severalfold.
    live_bytes = (live_before + eng.tick_bytes_estimate()) / 2
    return committed / wall, med, live_bytes


def _spec_phase(config, params, num_slots, max_len, prompt_len, ticks,
                draft_layers_full, draft_layers_cheap) -> dict:
    """Speculative-decoding ladder (ISSUE-17 tentpole): steady-state
    decode at ``spec_k`` in {0, 2, 4}, fresh engine per point, per-tick
    sync so the spec lever is isolated from fetch buffering. Three
    drafter settings per k: ``full_draft`` (target drafts for itself —
    accept 1.0 but full-priced draft passes, isolating the VERIFY
    path's k+1-tokens-per-param-stream win), ``cheap_draft`` (the
    honest truncated-layer default — random init gives it a near-zero
    accept rate, so this is the WORST case), and ``primed_draft`` (the
    truncated drafter against a target whose post-draft layers are
    residual identities — a high-accept workload with cheap drafts,
    standing in for a trained drafter on natural text). Reported per
    point: committed decode tokens/s, accept rate, committed tokens per
    tick, and the per-slot inter-token latency (TPOT) from committed
    counts. ``speedup_at_k4`` is primed_draft k=4 over the k=0 point —
    the >=1.5x acceptance criterion at >=0.5 accept."""
    from ray_tpu.models.continuous_batching import ContinuousBatcher

    # "primed" target: output projections of every layer past the cheap
    # draft depth zeroed, so those layers are exact residual identities
    # and the truncated drafter PREDICTS THE TARGET PERFECTLY. Random
    # init can't give a shallow drafter a real accept rate, so this
    # stands in for a trained drafter on natural text: a high-accept
    # workload with honestly-priced cheap draft passes — the regime the
    # >=1.5x acceptance bound is judged on. Same architecture, same
    # per-tick FLOPs and bytes as the random target.
    primed_layers = dict(params["layers"])
    for name in ("wo", "w_down"):
        primed_layers[name] = (
            primed_layers[name].at[draft_layers_cheap:].set(0))
    primed = dict(params, layers=primed_layers)

    grid = [(0, None, "base", params)]
    for k in (2, 4):
        grid.append((k, draft_layers_full, "full_draft", params))
        if draft_layers_cheap != draft_layers_full:
            grid.append((k, draft_layers_cheap, "cheap_draft", params))
        grid.append((k, draft_layers_cheap, "primed_draft", primed))
    points = []
    eng = None
    for k, dl, label, pp in grid:
        del eng  # release the previous point's arena first
        eng = ContinuousBatcher(config, params=pp,
                                num_slots=num_slots, max_len=max_len,
                                sync_every=1, paged=True,
                                spec_k=k, spec_draft_layers=dl,
                                spec_adaptive=False)
        tps, med, _ = _measure_decode(eng, num_slots, max_len,
                                      prompt_len, ticks)
        committed_per_tick = tps * med
        points.append({
            "label": label, "spec_k": k,
            "draft_layers": dl if k else None,
            "decode_tokens_per_s": round(tps, 1),
            "accept_rate": round(eng.spec_accept_rate, 4) if k else None,
            "committed_tokens_per_tick": round(committed_per_tick, 2),
            "tpot_ms": round(num_slots / tps * 1e3, 3),
            "mean_tick_ms": round(med * 1e3, 2),
            "tick_bytes_live": eng.tick_bytes_estimate(spec_k=k),
        })
    base_tps = points[0]["decode_tokens_per_s"]
    out = {"points": points}
    for p in points[1:]:
        if p["spec_k"] != 4:
            continue
        if p["label"] == "primed_draft":
            # The acceptance-criterion figure: cheap drafter, >=0.5
            # accept by construction.
            out["speedup_at_k4"] = round(
                p["decode_tokens_per_s"] / max(base_tps, 1e-9), 3)
            out["speedup_at_k4_accept_rate"] = p["accept_rate"]
        elif p["label"] == "full_draft":
            out["full_draft_speedup_at_k4"] = round(
                p["decode_tokens_per_s"] / max(base_tps, 1e-9), 3)
    return out


def _disagg_phase(config, params, num_slots, max_len, block_size,
                  long_prompt, short_prompt, long_new, short_new,
                  rounds) -> dict:
    """Disaggregated prefill/decode A/B (ISSUE-20 tentpole): the same
    mixed workload — alternating long-prefill requests (``long_prompt``
    tokens, ``short_new`` generated) and long-decode requests
    (``short_prompt`` tokens, ``long_new`` generated) — run colocated
    (one ``role="both"`` engine) and split (a ``role="prefill"`` engine
    handing finished KV blocks to a ``role="decode"`` engine over the
    REAL shm-channel path, ``kv_transfer.send_handoff`` →
    ``receive_handoff``). Client-visible TTFT for the split leg closes
    when ``receive_handoff`` returns: that is the moment the prefill's
    first token lands in a live decode slot and streams out. Reported:
    TTFT p50/p95 and TPOT each way, transfer bytes/s over the handoff
    wall, and the handoff latency breakdown (export/channel/import)
    from the decode engine's ``request_breakdowns`` — whose components
    must sum to the measured handoff wall
    (``breakdown_cover_frac`` ~ 1.0). Acceptance: split TTFT p95 <=
    colocated TTFT p95 on this mixed shape (long decodes hold
    colocated slots hostage; the dedicated prefill engine never
    waits on them)."""
    import numpy as _np

    from ray_tpu.models.continuous_batching import ContinuousBatcher
    from ray_tpu.serve import kv_transfer

    rng = _np.random.default_rng(23)

    # 1-in-4 requests is prefill-heavy, the rest decode-heavy: the
    # chat-fleet shape disaggregation exists for — long generations
    # hold colocated slots hostage while fresh prompts queue behind
    # them, which is exactly the contention the split topology removes.
    def _mixed(n):
        reqs = []
        for i in range(n):
            if i % 4 == 0:
                size, new = long_prompt, short_new   # prefill-heavy
            else:
                size, new = short_prompt, long_new   # decode-heavy
            reqs.append((list(map(int, rng.integers(
                1, config.vocab_size, size=size))), new))
        return reqs

    # Warm-up replays the exact workload shape with its own prompts:
    # same backlog size, same max_new mix — so every admission-batch
    # and prefill bucket the timed run hits is compiled, and the radix
    # cache cannot splice the timed prefills on either leg.
    warm = _mixed(rounds * num_slots)
    sched = _mixed(rounds * num_slots)

    def _pair(vals):
        v = sorted(vals)
        return (round(_pct(v, 0.50) * 1e3, 2),
                round(_pct(v, 0.95) * 1e3, 2))

    out = {"requests": len(sched),
           "long_prompt": long_prompt, "short_prompt": short_prompt,
           "long_new": long_new, "short_new": short_new}

    # ---- colocated leg: one engine does both phases; long decodes and
    # incoming prefills contend for the same slots and ticks.
    submit_ts = {}
    ttft = []

    def on_token(rid, _tok):
        t0 = submit_ts.pop(rid, None)
        if t0 is not None:
            ttft.append(time.perf_counter() - t0)

    colo = ContinuousBatcher(config, params=params, role="both",
                             num_slots=num_slots, max_len=max_len,
                             sync_every=1, paged=True,
                             block_size=block_size,
                             token_callback=on_token)
    def _run_colo(reqs):
        t0 = time.perf_counter()
        for prompt, n in reqs:  # full backlog up front, same both legs
            rid = colo.submit(list(prompt), max_new_tokens=n)
            submit_ts[rid] = time.perf_counter()
        while colo.has_work():
            colo.step()
        return time.perf_counter() - t0

    _run_colo(warm)
    ttft.clear()
    submit_ts.clear()
    colo.request_breakdowns.clear()
    colo_wall = _run_colo(sched)
    colo_p50, colo_p95 = _pair(ttft)
    colo_tpot = sorted(b["tpot_s"] for b in colo.request_breakdowns
                       if b.get("tpot_s") is not None)
    out["colocated"] = {
        "ttft_p50_ms": colo_p50, "ttft_p95_ms": colo_p95,
        "tpot_p50_ms": round(_pct(colo_tpot, 0.50) * 1e3, 3),
        "wall_s": round(colo_wall, 3)}
    del colo

    # ---- split leg: dedicated prefill engine exports each parked
    # request through a real shm channel into the decode engine, gated
    # on a free decode slot (production pre-reserves; the bench polls).
    pre = ContinuousBatcher(config, params=params, role="prefill",
                            num_slots=num_slots, max_len=max_len,
                            sync_every=1, paged=True,
                            block_size=block_size)
    # Role-specific sizing is one of disaggregation's levers: a decode
    # slot costs arena blocks, not prefill compute, so a decode-role
    # engine runs more concurrent generations than a colocated engine
    # (which must bound admission by prefill interference).
    decode_slots = 2 * num_slots
    dec = ContinuousBatcher(config, params=params, role="decode",
                            num_slots=decode_slots, max_len=max_len,
                            sync_every=1, paged=True,
                            block_size=block_size)
    submit_ts.clear()
    split_ttft = []
    handoff_walls = []
    xfer_bytes = 0

    def _run_split(reqs):
        nonlocal xfer_bytes
        inflight = []  # sent manifests waiting on a free decode slot
        t0 = time.perf_counter()
        for prompt, n in reqs:
            rid = pre.submit(list(prompt), max_new_tokens=n)
            submit_ts[rid] = time.perf_counter()
        while (pre.has_work() or pre.handoff_ready() or inflight
               or dec.has_work()):
            if pre.has_work():
                pre.step()
            for rid in list(pre.handoff_ready()):
                # Send frees the prefill slot/blocks immediately: the
                # bytes wait in the shm channel, never on the prefill
                # engine, so the next admission wave starts now.
                ts0 = time.perf_counter()
                m = kv_transfer.send_handoff(pre, rid,
                                             deployment="bench")
                m["journaled"] = True  # bench drives the transfer
                inflight.append(
                    (m, rid, time.perf_counter() - ts0))
            while inflight and dec._free:
                m, rid, send_s = inflight.pop(0)
                tr0 = time.perf_counter()
                kv_transfer.receive_handoff(dec, m, deployment="bench")
                now = time.perf_counter()
                # Transfer wall = send + receive durations; channel
                # queue time (waiting on a decode slot) is admission
                # pressure, not transfer cost.
                handoff_walls.append(send_s + (now - tr0))
                split_ttft.append(now - submit_ts.pop(rid))
                xfer_bytes += m["nbytes"]
            if dec.has_work():
                dec.step()
        return time.perf_counter() - t0

    _run_split(warm)
    submit_ts.clear()
    split_ttft.clear()
    handoff_walls.clear()
    xfer_bytes = 0
    pre.request_breakdowns.clear()
    dec.request_breakdowns.clear()
    split_wall = _run_split(sched)
    split_p50, split_p95 = _pair(split_ttft)
    split_tpot = sorted(b["tpot_s"] for b in dec.request_breakdowns
                        if b.get("tpot_s") is not None)
    comps = [b["handoff"] for b in dec.request_breakdowns
             if b.get("handoff")]
    breakdown = {}
    comp_total = 0.0
    for leg in ("export_s", "channel_s", "import_s"):
        vals = [c.get(leg, 0.0) for c in comps]
        comp_total += sum(vals)
        p50, p95 = _pair(vals)
        breakdown[leg.replace("_s", "_p50_ms")] = p50
        breakdown[leg.replace("_s", "_p95_ms")] = p95
    wall_total = sum(handoff_walls)
    out["split"] = {
        "decode_slots": decode_slots,
        "ttft_p50_ms": split_p50, "ttft_p95_ms": split_p95,
        "tpot_p50_ms": round(_pct(split_tpot, 0.50) * 1e3, 3),
        "wall_s": round(split_wall, 3),
        "transfer": {
            "handoffs": len(handoff_walls),
            "bytes_total": xfer_bytes,
            "bytes_per_s": round(xfer_bytes / max(wall_total, 1e-9), 1),
            "handoff_wall_p50_ms": _pair(handoff_walls)[0],
            "handoff_wall_p95_ms": _pair(handoff_walls)[1],
            "breakdown": breakdown,
            # export_s + channel_s + import_s over the measured wall —
            # the acceptance check that the breakdown accounts for the
            # handoff, not a fraction of it.
            "breakdown_cover_frac": round(
                comp_total / max(wall_total, 1e-9), 3),
        }}
    out["split_vs_colocated_ttft_p95"] = round(
        split_p95 / max(colo_p95, 1e-9), 3)
    kv_transfer.reap_channels(force=True)
    return out


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.models.continuous_batching import ContinuousBatcher

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        config = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16, head_dim=128,
            max_seq_len=2048)
        num_slots, max_len, prompt_len, ticks = 32, 512, 32, 120
        sync_every = 32  # remote-attached chip: ~90ms per host fetch
        sweep_grid = [(kv, bs) for kv in ("bf16", "int8")
                      for bs in (32, 64, 128)]
        sweep_ticks = 40
    else:  # CI fallback: always emit a line
        config = llama.LlamaConfig.tiny()
        num_slots, max_len, prompt_len, ticks = 4, 64, 8, 20
        sync_every = 4
        sweep_grid = [("bf16", 32), ("int8", 32)]
        sweep_ticks = 10

    # TTFT: submit timestamp per rid; first token closes the interval.
    submit_ts = {}
    ttft_s = []

    def on_token(rid, _tok):
        t0 = submit_ts.pop(rid, None)
        if t0 is not None:
            ttft_s.append(time.perf_counter() - t0)

    eng = ContinuousBatcher(config, num_slots=num_slots, max_len=max_len,
                            sync_every=sync_every, token_callback=on_token)
    param_bytes = eng.param_bytes

    def top_up(max_new=None, stamp=False):
        max_new = max_new if max_new is not None \
            else max_len - prompt_len - 1
        while len(eng._slots) + len(eng._waiting) < num_slots:
            rid = eng.submit(list(range(1, prompt_len + 1)),
                             max_new_tokens=max_new)
            if stamp:
                submit_ts[rid] = time.perf_counter()

    # Phase 1 — compile warm-up: a full admission burst + tick shapes.
    top_up(max_new=2)
    while eng.has_work():
        eng.step()

    # Phase 2 — churn (timed): short generations at full admission
    # pressure. The steady-state window below never frees a slot, so
    # TTFT (queueing included) and prefill throughput are measured here.
    ttft_s.clear()
    submit_ts.clear()
    eng.request_breakdowns.clear()
    prefill_tokens0 = eng.prefill_tokens
    prefill_seconds0 = eng.prefill_seconds
    for _ in range(2 * num_slots):
        rid = eng.submit(list(range(1, prompt_len + 1)), max_new_tokens=4)
        submit_ts[rid] = time.perf_counter()
    while eng.has_work():
        eng.step()
    prefill_tokens = eng.prefill_tokens - prefill_tokens0
    # Denominator is the engine's own dispatch->sync prefill interval, so
    # a decode-tick regression cannot masquerade as a prefill one.
    prefill_wall = max(eng.prefill_seconds - prefill_seconds0, 1e-9)
    # TTFT decomposition from the engine's request-path telemetry
    # (queue -> arena-wait -> prefill; the same records the
    # ray_tpu_serve_request_* histograms observe): the regression
    # baseline routing/admission changes are judged against — a router
    # change should move queue_ms, not prefill_ms. This churn phase has
    # NO shared prefixes, so it also guards the affinity-routing
    # acceptance bound (queue/prefill p95 must not regress when traffic
    # has nothing to share).
    ttft_breakdown = _breakdown_pcts(eng.request_breakdowns)

    # Phase 2c — prefix-reuse churn (ISSUE-8 tentpole): 75% of requests
    # share a block-aligned system prompt; the radix cache must turn
    # their prefills into table splices. Acceptance: >=2x effective
    # prefill tokens/s (or >=50% prefill_tokens_saved) at 75% shared
    # traffic.
    if on_tpu:
        prefix_phase = _prefix_phase(config, eng.params, num_slots,
                                     max_len, sync_every, block_size=64,
                                     shared_blocks=4, tail_len=16,
                                     rounds=4)
    else:
        prefix_phase = _prefix_phase(config, eng.params, num_slots,
                                     max_len=64, sync_every=1,
                                     block_size=8, shared_blocks=4,
                                     tail_len=4, rounds=2)

    # Phase 2d — speculative-decoding ladder (ISSUE-17 tentpole):
    # committed decode tokens/s at spec_k in {0, 2, 4}; full-depth
    # self-draft isolates the batched-verify win at accept-rate 1.0,
    # the truncated default shows the honest operating point.
    if on_tpu:
        spec_phase = _spec_phase(config, eng.params, num_slots, max_len,
                                 prompt_len, ticks=60,
                                 draft_layers_full=config.num_layers,
                                 draft_layers_cheap=max(
                                     1, config.num_layers // 4))
    else:
        spec_phase = _spec_phase(config, eng.params, num_slots,
                                 max_len=64, prompt_len=8, ticks=12,
                                 draft_layers_full=config.num_layers,
                                 draft_layers_cheap=1)

    # Phase 2e — disaggregated prefill/decode A/B (ISSUE-20 tentpole):
    # the same mixed long-prefill/long-decode backlog colocated vs
    # split over the KV-block channel plane. Acceptance: split TTFT
    # p95 <= colocated TTFT p95, breakdown components sum to the
    # handoff wall.
    if on_tpu:
        disagg_phase = _disagg_phase(config, eng.params, num_slots,
                                     max_len=512, block_size=64,
                                     long_prompt=256, short_prompt=32,
                                     long_new=128, short_new=8,
                                     rounds=2)
    else:
        disagg_phase = _disagg_phase(config, eng.params, num_slots=4,
                                     max_len=128, block_size=16,
                                     long_prompt=40, short_prompt=8,
                                     long_new=80, short_new=4,
                                     rounds=3)

    # Phase 3 — steady-state decode at full occupancy. No per-tick
    # device sync: the buffered engine's whole point is overlapping
    # fetches with compute, so the wall clock over the window is the
    # honest measure.
    tokens_per_s, med, live_bytes = _measure_decode(
        eng, num_slots, max_len, prompt_len, ticks)
    # Capture the MAIN engine's compiled-tick cost now: the sweep below
    # recompiles cb_tick per config and would otherwise overwrite it.
    cost_bytes, tick_flops = _tick_cost_stats()

    # Roofline: params + average live KV prefix, read once per tick,
    # priced at the engine's OWN storage bytes-per-token (paged arena or
    # dense bf16). The 10%-of-bf16-dense criterion stays fixed across
    # configs so vs_baseline remains comparable round over round.
    avg_pos = (prompt_len + max_len) / 2
    if eng.paged:
        per_token = eng.cache.token_bytes()
    else:
        per_token = (2 * config.num_layers * config.num_kv_heads
                     * config.head_dim
                     * jnp.dtype(config.dtype).itemsize)
    kv_bytes = num_slots * avg_pos * per_token
    bf16_per_token = (2 * config.num_layers * config.num_kv_heads
                      * config.head_dim * 2)
    bw = _hbm_bw(jax.devices()[0])
    roofline = num_slots * bw / (param_bytes + kv_bytes)
    criterion = 0.10 * (num_slots * bw / (param_bytes + num_slots
                                          * avg_pos * bf16_per_token))

    # kv_dtype x block_size sweep: short steady-state windows, each on a
    # fresh engine (fresh compile), reporting tokens/s + both byte
    # figures. The live figure must track live tokens; the cost figure
    # shows what the compiler statically prices.
    sweep = []
    s_eng = None
    for kv_dtype, bs in sweep_grid:
        del s_eng  # release the previous config's arena before allocating
        s_eng = ContinuousBatcher(config, num_slots=num_slots,
                                  max_len=max_len, sync_every=sync_every,
                                  paged=True, block_size=bs,
                                  kv_dtype=kv_dtype, params=eng.params)
        tps, _, lb = _measure_decode(s_eng, num_slots, max_len,
                                     prompt_len, sweep_ticks)
        sweep.append({
            "kv_dtype": kv_dtype, "block_size": bs,
            "tokens_per_s": round(tps, 1),
            "bytes_read_per_tick_cost": _tick_cost_stats()[0],
            "bytes_read_per_tick_live": int(lb),
        })

    ttft_sorted = sorted(ttft_s)
    out = {
        "metric": "decode_tokens_per_s_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_s / criterion, 3),
        "roofline_tokens_per_s": round(roofline, 1),
        "hbm_efficiency": round(tokens_per_s / roofline, 3),
        "mean_tick_ms": round(med * 1e3, 2),
        "ttft_p50_ms": round(_pct(ttft_sorted, 0.50) * 1e3, 2),
        "ttft_p95_ms": round(_pct(ttft_sorted, 0.95) * 1e3, 2),
        "ttft_samples": len(ttft_sorted),
        "ttft_breakdown": ttft_breakdown,
        "prefix_phase": prefix_phase,
        "spec_phase": spec_phase,
        "disagg_phase": disagg_phase,
        "prefill_tokens_per_s": round(prefill_tokens / prefill_wall, 1),
        # Live-token accounting is the headline figure (it is what the
        # achieved-BW gauges use); the static cost-analysis figure rides
        # along for the worst-case comparison. (The r05-era
        # bytes_read_per_tick_est key is dropped rather than silently
        # repointed at a different quantity.)
        "bytes_read_source": "live_estimate",
        "bytes_read_per_tick_cost": cost_bytes,
        "bytes_read_per_tick_live": int(live_bytes),
        "tick_flops": tick_flops,
        "decode_kernel": eng.use_decode_kernel,
        "paged": eng.paged,
        "block_size": eng.block_size if eng.paged else None,
        "kv_dtype": eng.kv_dtype,
        "sweep": sweep,
        "num_slots": num_slots,
        "sync_every": sync_every,
        "param_bytes": param_bytes,
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "on_tpu": on_tpu,
    }
    print(json.dumps(out))
    rnd = int(sys.argv[sys.argv.index("--round") + 1]) \
        if "--round" in sys.argv else 5
    with open(f"BENCH_SERVE_r{rnd:02d}.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
