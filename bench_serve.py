"""Serving benchmark: continuous-batching decode throughput on one chip.

Prints ONE JSON line and writes ``BENCH_SERVE_r{N}.json``.

Metric: steady-state decode tokens/sec/chip of the ContinuousBatcher
(``models/continuous_batching.py``) running the same ~1B-param Llama the
training bench uses, all KV slots saturated. Also reported: time-to-
first-token (submit -> first streamed token, p50/p95 over every request
admitted during the run), prefill tokens/s, and a per-tick bytes-read
figure — the tick program's ``cost_analysis()`` harvested by the XLA
monitor when the backend provides one (``bytes_read_source:
cost_analysis``), the hand estimate otherwise — so ``hbm_efficiency``
regressions are attributable to a specific traffic term (params vs KV
vs upcast copies).

Criterion (v5e HBM roofline): every decode tick must read the full
parameter set plus the active KV prefixes from HBM, so
``roofline_tokens_per_s = num_slots * HBM_BW / (param_bytes + kv_bytes)``.
The criterion is 10% of this roofline: XLA (non-pallas) decode with
per-slot cache scatter plus a REMOTE-attached chip (every host fetch
costs a ~90ms tunnel RTT; the engine's speculative buffered decode hides
most but not all of it) lands 10-15%; the fused pallas decode kernel
(``ops/decode_attention.py``, reads K/V once in bf16 instead of twice in
fp32) plus bf16 lm_head targets >=25%; vLLM-class stacks on local GPUs
land ~15-30%. ``vs_baseline`` = achieved / (0.10 * roofline), and
``hbm_efficiency`` reports the raw fraction transparently.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

HBM_GBPS = {
    "TPU v5 lite": 819e9,   # v5e
    "TPU v5": 2765e9,       # v5p
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,  # v6e
}


def _hbm_bw(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, bw in HBM_GBPS.items():
        if kind.startswith(name):
            return bw
    return 819e9


def _pct(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.models.continuous_batching import ContinuousBatcher

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        config = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16, head_dim=128,
            max_seq_len=2048)
        num_slots, max_len, prompt_len, ticks = 32, 512, 32, 120
        sync_every = 32  # remote-attached chip: ~90ms per host fetch
    else:  # CI fallback: always emit a line
        config = llama.LlamaConfig.tiny()
        num_slots, max_len, prompt_len, ticks = 4, 64, 8, 20
        sync_every = 4

    # TTFT: submit timestamp per rid; first token closes the interval.
    submit_ts = {}
    ttft_s = []

    def on_token(rid, _tok):
        t0 = submit_ts.pop(rid, None)
        if t0 is not None:
            ttft_s.append(time.perf_counter() - t0)

    eng = ContinuousBatcher(config, num_slots=num_slots, max_len=max_len,
                            sync_every=sync_every, token_callback=on_token)
    param_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(eng.params))

    def top_up(max_new=None, stamp=False):
        max_new = max_new if max_new is not None \
            else max_len - prompt_len - 1
        while len(eng._slots) + len(eng._waiting) < num_slots:
            rid = eng.submit(list(range(1, prompt_len + 1)),
                             max_new_tokens=max_new)
            if stamp:
                submit_ts[rid] = time.perf_counter()

    # Phase 1 — compile warm-up: a full admission burst + tick shapes.
    top_up(max_new=2)
    while eng.has_work():
        eng.step()

    # Phase 2 — churn (timed): short generations at full admission
    # pressure. The steady-state window below never frees a slot, so
    # TTFT (queueing included) and prefill throughput are measured here.
    ttft_s.clear()
    submit_ts.clear()
    prefill_tokens0 = eng.prefill_tokens
    prefill_seconds0 = eng.prefill_seconds
    for _ in range(2 * num_slots):
        rid = eng.submit(list(range(1, prompt_len + 1)), max_new_tokens=4)
        submit_ts[rid] = time.perf_counter()
    while eng.has_work():
        eng.step()
    prefill_tokens = eng.prefill_tokens - prefill_tokens0
    # Denominator is the engine's own dispatch->sync prefill interval, so
    # a decode-tick regression cannot masquerade as a prefill one.
    prefill_wall = max(eng.prefill_seconds - prefill_seconds0, 1e-9)

    # Phase 3 — steady-state decode at full occupancy. No per-tick
    # device sync: the buffered engine's whole point is overlapping
    # fetches with compute, so the wall clock over the window is the
    # honest measure.
    top_up()
    for _ in range(5):
        eng.step()
        top_up()
    t0 = time.perf_counter()
    for _ in range(ticks):
        top_up()
        eng.step()
    jax.block_until_ready(eng.cache.k)
    wall = time.perf_counter() - t0
    med = wall / ticks
    tokens_per_s = num_slots / med

    # Roofline: params + average live KV prefix, read once per tick.
    avg_pos = (prompt_len + max_len) / 2
    kv_itemsize = jnp.dtype(config.dtype).itemsize
    kv_bytes = (num_slots * avg_pos * config.num_layers
                * 2 * config.num_kv_heads * config.head_dim * kv_itemsize)
    bw = _hbm_bw(jax.devices()[0])
    roofline = num_slots * bw / (param_bytes + kv_bytes)
    criterion = 0.10 * roofline
    # What one tick SHOULD read at minimum (kernel on: params once + live
    # KV once in storage dtype). The reference XLA path reads the KV pool
    # twice per layer in fp32 (QK^T and PV upcasts) — ~4x kv_bytes —
    # which is exactly the traffic the fused kernel removes; comparing
    # hbm_efficiency against this floor attributes a regression.
    bytes_read_per_tick = param_bytes + kv_bytes
    bytes_source = "estimate"
    # Prefer the compiler's own answer: the XLA monitor harvested the
    # tick program's cost_analysis() at compile time (bytes accessed per
    # invocation). The hand estimate stays as the fallback — some
    # backends return no cost analysis.
    from ray_tpu._private import xla_monitor

    tick_stats = xla_monitor.program_stats("cb_tick") or {}
    if tick_stats.get("bytes_accessed"):
        bytes_read_per_tick = tick_stats["bytes_accessed"]
        bytes_source = "cost_analysis"

    ttft_sorted = sorted(ttft_s)
    out = {
        "metric": "decode_tokens_per_s_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_s / criterion, 3),
        "roofline_tokens_per_s": round(roofline, 1),
        "hbm_efficiency": round(tokens_per_s / roofline, 3),
        "mean_tick_ms": round(med * 1e3, 2),
        "ttft_p50_ms": round(_pct(ttft_sorted, 0.50) * 1e3, 2),
        "ttft_p95_ms": round(_pct(ttft_sorted, 0.95) * 1e3, 2),
        "ttft_samples": len(ttft_sorted),
        "prefill_tokens_per_s": round(prefill_tokens / prefill_wall, 1),
        "bytes_read_per_tick_est": int(bytes_read_per_tick),
        "bytes_read_source": bytes_source,
        "tick_flops": int(tick_stats.get("flops", 0)),
        "decode_kernel": eng.use_decode_kernel,
        "num_slots": num_slots,
        "sync_every": sync_every,
        "param_bytes": param_bytes,
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "on_tpu": on_tpu,
    }
    print(json.dumps(out))
    rnd = int(sys.argv[sys.argv.index("--round") + 1]) \
        if "--round" in sys.argv else 5
    with open(f"BENCH_SERVE_r{rnd:02d}.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
