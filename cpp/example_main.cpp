// C++ worker API end-to-end example (driven by tests/test_cpp_api.py).
//
// Connects to a ClientGateway, exercises KV, Put/Get, and remote task
// submission of Python-registered cross-language functions, printing
// CHECK lines the test asserts on.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "ray_tpu/api.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <gateway_port>\n", argv[0]);
    return 2;
  }
  ray_tpu::Client client;
  if (!client.Connect("127.0.0.1", std::atoi(argv[1]))) {
    std::fprintf(stderr, "connect failed: %s\n",
                 client.last_error().c_str());
    return 1;
  }

  // KV round-trip.
  if (!client.KvPut("cpp", "greeting", "hello from c++")) return 1;
  std::string got;
  if (!client.KvGet("cpp", "greeting", &got) || got != "hello from c++")
    return 1;
  std::printf("CHECK kv ok\n");

  // Object put/get round-trip.
  std::string oid = client.Put(ray_tpu::V(static_cast<int64_t>(41)));
  if (oid.empty()) return 1;
  ray_tpu::rpc::XLangValue out;
  std::string err;
  if (!client.Get(oid, &out, &err) || out.i() != 41) {
    std::fprintf(stderr, "put/get failed: %s\n", err.c_str());
    return 1;
  }
  std::printf("CHECK put_get ok\n");

  // Remote task: Python-side `add(a, b)`.
  std::string ref = client.Submit(
      "add", {ray_tpu::V(static_cast<int64_t>(2)),
              ray_tpu::V(static_cast<int64_t>(3))});
  if (ref.empty()) {
    std::fprintf(stderr, "submit failed: %s\n", client.last_error().c_str());
    return 1;
  }
  if (!client.Get(ref, &out, &err) || out.i() != 5) {
    std::fprintf(stderr, "task failed: %s\n", err.c_str());
    return 1;
  }
  std::printf("CHECK task add=5 ok\n");

  // Remote task with string payloads + explicit CPU demand.
  ref = client.Submit("shout", {ray_tpu::V(std::string("tpu"))},
                      {{"CPU", 1.0}});
  if (ref.empty() || !client.Get(ref, &out, &err) || out.s() != "TPU!") {
    std::fprintf(stderr, "shout failed: %s\n", err.c_str());
    return 1;
  }
  std::printf("CHECK task shout ok\n");

  // Error propagation from a failing Python task.
  ref = client.Submit("boom", {});
  if (ref.empty()) return 1;
  if (client.Get(ref, &out, &err)) return 1;  // must fail
  if (err.find("boom!") == std::string::npos) {
    std::fprintf(stderr, "unexpected error text: %s\n", err.c_str());
    return 1;
  }
  std::printf("CHECK task error propagated\n");

  // Release gateway-held pins.
  if (!client.Free(oid) || !client.Free(ref)) return 1;
  if (client.Get(oid, &out, &err)) return 1;  // freed -> unknown id
  std::printf("CHECK free ok\n");

  // Actor lifecycle through the gateway: create a registered Python
  // class, call methods (stateful), kill it.
  std::string actor = client.CreateActor(
      "Counter", {ray_tpu::V(static_cast<int64_t>(100))});
  if (actor.empty()) {
    std::fprintf(stderr, "CreateActor failed: %s\n",
                 client.last_error().c_str());
    return 1;
  }
  for (int i = 0; i < 3; ++i) {
    ref = client.ActorCall(actor, "add", {ray_tpu::V(int64_t(7))});
    if (ref.empty() || !client.Get(ref, &out, &err)) return 1;
  }
  if (out.i() != 121) {  // 100 + 3*7: state persisted across calls
    std::fprintf(stderr, "actor state wrong: %lld\n",
                 static_cast<long long>(out.i()));
    return 1;
  }
  if (!client.KillActor(actor)) return 1;
  if (!client.ActorCall(actor, "add", {ray_tpu::V(int64_t(1))}).empty())
    return 1;  // killed actor: unknown id
  std::printf("CHECK actor ok\n");

  // With --call-cpp: a C++-registered task (served by a TaskExecutor
  // worker process) reached through the same gateway Submit path.
  if (argc > 2 && std::string(argv[2]) == "--call-cpp") {
    ref = client.Submit("cpp_mul", {ray_tpu::V(static_cast<int64_t>(6)),
                                    ray_tpu::V(static_cast<int64_t>(9))});
    if (ref.empty() || !client.Get(ref, &out, &err) || out.i() != 54) {
      std::fprintf(stderr, "cpp_worker call failed: %s\n", err.c_str());
      return 1;
    }
    std::printf("CHECK cpp_worker mul=54 ok\n");
  }

  std::printf("ALL CHECKS PASSED\n");
  return 0;
}
