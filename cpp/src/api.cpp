// ray_tpu C++ worker API implementation — see include/ray_tpu/api.h.

#include "ray_tpu/api.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

namespace ray_tpu {

namespace {
// Gateway ops (must match ray_tpu/cross_language.py).
constexpr uint8_t kOpKvPut = 1;
constexpr uint8_t kOpKvGet = 2;
constexpr uint8_t kOpPut = 3;
constexpr uint8_t kOpGet = 4;
constexpr uint8_t kOpSubmit = 5;
constexpr uint8_t kOpWait = 6;
constexpr uint8_t kOpFree = 7;

// The wire protocol is explicitly little-endian; encode/decode byte-wise
// so the client also works on big-endian hosts.
void PutU32LE(uint32_t v, char* out) {
  out[0] = static_cast<char>(v & 0xff);
  out[1] = static_cast<char>((v >> 8) & 0xff);
  out[2] = static_cast<char>((v >> 16) & 0xff);
  out[3] = static_cast<char>((v >> 24) & 0xff);
}

uint32_t GetU32LE(const char* in) {
  return static_cast<uint32_t>(static_cast<unsigned char>(in[0])) |
         (static_cast<uint32_t>(static_cast<unsigned char>(in[1])) << 8) |
         (static_cast<uint32_t>(static_cast<unsigned char>(in[2])) << 16) |
         (static_cast<uint32_t>(static_cast<unsigned char>(in[3])) << 24);
}
}  // namespace

rpc::XLangValue V(double d) {
  rpc::XLangValue v;
  v.set_d(d);
  return v;
}
rpc::XLangValue V(int64_t i) {
  rpc::XLangValue v;
  v.set_i(i);
  return v;
}
rpc::XLangValue V(const std::string& s) {
  rpc::XLangValue v;
  v.set_s(s);
  return v;
}
rpc::XLangValue VBytes(const std::string& b) {
  rpc::XLangValue v;
  v.set_b(b);
  return v;
}
rpc::XLangValue VBool(bool f) {
  rpc::XLangValue v;
  v.set_flag(f);
  return v;
}

Client::~Client() { Close(); }

void Client::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Client::Connect(const std::string& host, int port) {
  Close();
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) {
    last_error_ = "socket() failed";
    return false;
  }
  int one = 1;
  ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    last_error_ = "bad host address";
    Close();
    return false;
  }
  if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    last_error_ = "connect() failed";
    Close();
    return false;
  }
  return true;
}

bool Client::SendAll(const char* data, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd_, data + sent, n - sent, 0);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool Client::RecvAll(char* data, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd_, data + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool Client::Call(uint8_t op, const std::string& body, std::string* reply) {
  if (fd_ < 0) {
    last_error_ = "not connected";
    return false;
  }
  // Frame: [u32le len][u8 op][body]; reply [u32le len][u8 ok][body].
  char header[5];
  PutU32LE(static_cast<uint32_t>(body.size()), header);
  header[4] = static_cast<char>(op);
  if (!SendAll(header, 5) || !SendAll(body.data(), body.size())) {
    last_error_ = "send failed";
    Close();
    return false;
  }
  char rhead[5];
  if (!RecvAll(rhead, 5)) {
    last_error_ = "recv failed";
    Close();
    return false;
  }
  uint32_t rlen = GetU32LE(rhead);
  reply->resize(rlen);
  if (rlen > 0 && !RecvAll(&(*reply)[0], rlen)) {
    last_error_ = "recv failed";
    Close();
    return false;
  }
  if (rhead[4] == 0) {
    last_error_ = *reply;  // gateway sends the error text as the body
    return false;
  }
  return true;
}

std::string Client::Put(const rpc::XLangValue& value) {
  std::string reply;
  if (!Call(kOpPut, value.SerializeAsString(), &reply)) return "";
  rpc::GatewayRef ref;
  if (!ref.ParseFromString(reply)) {
    last_error_ = "bad GatewayRef reply";
    return "";
  }
  return ref.object_id();
}

std::string Client::Submit(const std::string& function,
                           const std::vector<rpc::XLangValue>& args,
                           const std::map<std::string, double>& resources) {
  rpc::XLangCall call;
  call.set_function(function);
  for (const auto& a : args) *call.add_args() = a;
  for (const auto& kv : resources)
    (*call.mutable_resources())[kv.first] = kv.second;
  std::string reply;
  if (!Call(kOpSubmit, call.SerializeAsString(), &reply)) return "";
  rpc::GatewayRef ref;
  if (!ref.ParseFromString(reply)) {
    last_error_ = "bad GatewayRef reply";
    return "";
  }
  return ref.object_id();
}

bool Client::Get(const std::string& object_id, rpc::XLangValue* out,
                 std::string* error) {
  rpc::GatewayRef ref;
  ref.set_object_id(object_id);
  std::string reply;
  if (!Call(kOpGet, ref.SerializeAsString(), &reply)) {
    if (error) *error = last_error_;
    return false;
  }
  rpc::XLangResult result;
  if (!result.ParseFromString(reply)) {
    last_error_ = "bad XLangResult reply";
    if (error) *error = last_error_;
    return false;
  }
  if (!result.ok()) {
    if (error) *error = result.error();
    return false;
  }
  *out = result.value();
  return true;
}

bool Client::Wait(const std::string& object_id) {
  rpc::GatewayRef ref;
  ref.set_object_id(object_id);
  std::string reply;
  if (!Call(kOpWait, ref.SerializeAsString(), &reply)) return false;
  rpc::XLangResult result;
  return result.ParseFromString(reply) && result.ok();
}

bool Client::Free(const std::string& object_id) {
  rpc::GatewayRef ref;
  ref.set_object_id(object_id);
  std::string reply;
  if (!Call(kOpFree, ref.SerializeAsString(), &reply)) return false;
  rpc::XLangResult result;
  return result.ParseFromString(reply) && result.ok();
}

bool Client::KvPut(const std::string& ns, const std::string& key,
                   const std::string& value) {
  rpc::KvRequest req;
  req.set_ns(ns);
  req.set_key(key);
  req.set_value(value);
  req.set_overwrite(true);
  std::string reply;
  if (!Call(kOpKvPut, req.SerializeAsString(), &reply)) return false;
  rpc::KvReply kv;
  return kv.ParseFromString(reply) && kv.ok();
}

bool Client::KvGet(const std::string& ns, const std::string& key,
                   std::string* value) {
  rpc::KvRequest req;
  req.set_ns(ns);
  req.set_key(key);
  std::string reply;
  if (!Call(kOpKvGet, req.SerializeAsString(), &reply)) return false;
  rpc::KvReply kv;
  if (!kv.ParseFromString(reply) || !kv.found()) return false;
  *value = kv.value();
  return true;
}

}  // namespace ray_tpu
