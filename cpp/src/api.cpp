// ray_tpu C++ worker API implementation — see include/ray_tpu/api.h.

#include "ray_tpu/api.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

namespace ray_tpu {

namespace {
// Gateway ops (must match ray_tpu/cross_language.py).
constexpr uint8_t kOpKvPut = 1;
constexpr uint8_t kOpKvGet = 2;
constexpr uint8_t kOpPut = 3;
constexpr uint8_t kOpGet = 4;
constexpr uint8_t kOpSubmit = 5;
constexpr uint8_t kOpWait = 6;
constexpr uint8_t kOpFree = 7;
constexpr uint8_t kOpCreateActor = 8;
constexpr uint8_t kOpActorCall = 9;
constexpr uint8_t kOpKillActor = 10;

// The wire protocol is explicitly little-endian; encode/decode byte-wise
// so the client also works on big-endian hosts.
void PutU32LE(uint32_t v, char* out) {
  out[0] = static_cast<char>(v & 0xff);
  out[1] = static_cast<char>((v >> 8) & 0xff);
  out[2] = static_cast<char>((v >> 16) & 0xff);
  out[3] = static_cast<char>((v >> 24) & 0xff);
}

uint32_t GetU32LE(const char* in) {
  return static_cast<uint32_t>(static_cast<unsigned char>(in[0])) |
         (static_cast<uint32_t>(static_cast<unsigned char>(in[1])) << 8) |
         (static_cast<uint32_t>(static_cast<unsigned char>(in[2])) << 16) |
         (static_cast<uint32_t>(static_cast<unsigned char>(in[3])) << 24);
}

bool SendAllFd(int fd, const char* data, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = ::send(fd, data + off, n - off, 0);
    if (w <= 0) return false;
    off += static_cast<size_t>(w);
  }
  return true;
}

bool RecvAllFd(int fd, char* data, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, data + off, n - off, 0);
    if (r <= 0) return false;
    off += static_cast<size_t>(r);
  }
  return true;
}
}  // namespace

rpc::XLangValue V(double d) {
  rpc::XLangValue v;
  v.set_d(d);
  return v;
}
rpc::XLangValue V(int64_t i) {
  rpc::XLangValue v;
  v.set_i(i);
  return v;
}
rpc::XLangValue V(const std::string& s) {
  rpc::XLangValue v;
  v.set_s(s);
  return v;
}
rpc::XLangValue VBytes(const std::string& b) {
  rpc::XLangValue v;
  v.set_b(b);
  return v;
}
rpc::XLangValue VBool(bool f) {
  rpc::XLangValue v;
  v.set_flag(f);
  return v;
}

Client::~Client() { Close(); }

void Client::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Client::Connect(const std::string& host, int port) {
  Close();
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) {
    last_error_ = "socket() failed";
    return false;
  }
  int one = 1;
  ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    last_error_ = "bad host address";
    Close();
    return false;
  }
  if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    last_error_ = "connect() failed";
    Close();
    return false;
  }
  return true;
}

std::string Client::LocalAddress() const {
  if (fd_ < 0) return "127.0.0.1";
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len) != 0)
    return "127.0.0.1";
  char buf[INET_ADDRSTRLEN];
  if (!::inet_ntop(AF_INET, &addr.sin_addr, buf, sizeof(buf)))
    return "127.0.0.1";
  return buf;
}

bool Client::SendAll(const char* data, size_t n) {
  return SendAllFd(fd_, data, n);
}

bool Client::RecvAll(char* data, size_t n) {
  return RecvAllFd(fd_, data, n);
}

bool Client::Call(uint8_t op, const std::string& body, std::string* reply) {
  if (fd_ < 0) {
    last_error_ = "not connected";
    return false;
  }
  // Frame: [u32le len][u8 op][body]; reply [u32le len][u8 ok][body].
  char header[5];
  PutU32LE(static_cast<uint32_t>(body.size()), header);
  header[4] = static_cast<char>(op);
  if (!SendAll(header, 5) || !SendAll(body.data(), body.size())) {
    last_error_ = "send failed";
    Close();
    return false;
  }
  char rhead[5];
  if (!RecvAll(rhead, 5)) {
    last_error_ = "recv failed";
    Close();
    return false;
  }
  uint32_t rlen = GetU32LE(rhead);
  reply->resize(rlen);
  if (rlen > 0 && !RecvAll(&(*reply)[0], rlen)) {
    last_error_ = "recv failed";
    Close();
    return false;
  }
  if (rhead[4] == 0) {
    last_error_ = *reply;  // gateway sends the error text as the body
    return false;
  }
  return true;
}

std::string Client::Put(const rpc::XLangValue& value) {
  std::string reply;
  if (!Call(kOpPut, value.SerializeAsString(), &reply)) return "";
  rpc::GatewayRef ref;
  if (!ref.ParseFromString(reply)) {
    last_error_ = "bad GatewayRef reply";
    return "";
  }
  return ref.object_id();
}

std::string Client::CallReturningRef(uint8_t op, const std::string& body) {
  std::string reply;
  if (!Call(op, body, &reply)) return "";
  rpc::GatewayRef ref;
  if (!ref.ParseFromString(reply)) {
    last_error_ = "bad GatewayRef reply";
    return "";
  }
  return ref.object_id();
}

bool Client::CallReturningOk(uint8_t op, const std::string& body) {
  std::string reply;
  if (!Call(op, body, &reply)) return false;
  rpc::XLangResult result;
  return result.ParseFromString(reply) && result.ok();
}

namespace {
rpc::XLangCall BuildCall(const std::string& function,
                         const std::vector<rpc::XLangValue>& args,
                         const std::map<std::string, double>& resources) {
  rpc::XLangCall call;
  call.set_function(function);
  for (const auto& a : args) *call.add_args() = a;
  for (const auto& kv : resources)
    (*call.mutable_resources())[kv.first] = kv.second;
  return call;
}
}  // namespace

std::string Client::Submit(const std::string& function,
                           const std::vector<rpc::XLangValue>& args,
                           const std::map<std::string, double>& resources) {
  return CallReturningRef(
      kOpSubmit, BuildCall(function, args, resources).SerializeAsString());
}

bool Client::Get(const std::string& object_id, rpc::XLangValue* out,
                 std::string* error) {
  rpc::GatewayRef ref;
  ref.set_object_id(object_id);
  std::string reply;
  if (!Call(kOpGet, ref.SerializeAsString(), &reply)) {
    if (error) *error = last_error_;
    return false;
  }
  rpc::XLangResult result;
  if (!result.ParseFromString(reply)) {
    last_error_ = "bad XLangResult reply";
    if (error) *error = last_error_;
    return false;
  }
  if (!result.ok()) {
    if (error) *error = result.error();
    return false;
  }
  *out = result.value();
  return true;
}

bool Client::Wait(const std::string& object_id) {
  rpc::GatewayRef ref;
  ref.set_object_id(object_id);
  return CallReturningOk(kOpWait, ref.SerializeAsString());
}

bool Client::Free(const std::string& object_id) {
  rpc::GatewayRef ref;
  ref.set_object_id(object_id);
  return CallReturningOk(kOpFree, ref.SerializeAsString());
}

std::string Client::CreateActor(
    const std::string& class_name,
    const std::vector<rpc::XLangValue>& args,
    const std::map<std::string, double>& resources) {
  return CallReturningRef(
      kOpCreateActor,
      BuildCall(class_name, args, resources).SerializeAsString());
}

std::string Client::ActorCall(const std::string& actor_id,
                              const std::string& method,
                              const std::vector<rpc::XLangValue>& args) {
  rpc::XLangActorCall call;
  call.set_actor_id(actor_id);
  call.set_method(method);
  for (const auto& a : args) *call.add_args() = a;
  return CallReturningRef(kOpActorCall, call.SerializeAsString());
}

bool Client::KillActor(const std::string& actor_id) {
  rpc::GatewayRef ref;
  ref.set_object_id(actor_id);
  return CallReturningOk(kOpKillActor, ref.SerializeAsString());
}

bool Client::KvPut(const std::string& ns, const std::string& key,
                   const std::string& value) {
  rpc::KvRequest req;
  req.set_ns(ns);
  req.set_key(key);
  req.set_value(value);
  req.set_overwrite(true);
  std::string reply;
  if (!Call(kOpKvPut, req.SerializeAsString(), &reply)) return false;
  rpc::KvReply kv;
  return kv.ParseFromString(reply) && kv.ok();
}

bool Client::KvGet(const std::string& ns, const std::string& key,
                   std::string* value) {
  rpc::KvRequest req;
  req.set_ns(ns);
  req.set_key(key);
  std::string reply;
  if (!Call(kOpKvGet, req.SerializeAsString(), &reply)) return false;
  rpc::KvReply kv;
  if (!kv.ParseFromString(reply) || !kv.found()) return false;
  *value = kv.value();
  return true;
}

// ------------------------------------------------------------- TaskExecutor

TaskExecutor::~TaskExecutor() { Stop(); }

void TaskExecutor::RegisterActorClass(const std::string& name,
                                      CppActorFactory factory) {
  actor_classes_[name] = std::move(factory);
}

void TaskExecutor::Register(const std::string& name, CppTaskFn fn) {
  fns_[name] = std::move(fn);
}

int TaskExecutor::Serve(Client& gateway, const std::string& advertise_host,
                        int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return 0;
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 16) != 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
    return 0;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  // Announce every function: KV "__cpp_executors__"/<name> -> host:port.
  // Empty advertise_host: use the address this host reaches the gateway
  // from — routable by other nodes, unlike loopback.
  const std::string host =
      advertise_host.empty() ? gateway.LocalAddress() : advertise_host;
  const std::string address = host + ":" + std::to_string(port_);
  for (const auto& kv : fns_) {
    if (!gateway.KvPut("__cpp_executors__", kv.first, address)) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return 0;
    }
  }
  // Actor classes announce in their own namespace so Python's
  // cpp_actor_class() / the gateway's CreateActor can route to us.
  for (const auto& kv : actor_classes_) {
    if (!gateway.KvPut("__cpp_actor_classes__", kv.first, address)) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return 0;
    }
  }
  accept_thread_ = std::thread([this] { AcceptLoop(); });
  return port_;
}

void TaskExecutor::Stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  // Wake threads blocked in recv() on idle keep-alive connections —
  // without the shutdown, join() below would hang forever.
  for (auto& c : conns_) {
    ::shutdown(c.fd, SHUT_RDWR);
  }
  for (auto& c : conns_) {
    if (c.thread.joinable()) c.thread.join();
  }
  conns_.clear();
}

void TaskExecutor::AcceptLoop() {
  while (!stopping_.load()) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    // Reap finished connection threads (per-call clients would otherwise
    // accumulate one unjoined thread per connection forever).
    for (auto it = conns_.begin(); it != conns_.end();) {
      if (it->done->load()) {
        if (it->thread.joinable()) it->thread.join();
        it = conns_.erase(it);
      } else {
        ++it;
      }
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto done = std::make_shared<std::atomic<bool>>(false);
    Conn c;
    c.fd = fd;
    c.done = done;
    c.thread = std::thread([this, fd, done] { ServeConn(fd, done); });
    conns_.push_back(std::move(c));
  }
}

rpc::XLangResult TaskExecutor::HandleActorOp(uint8_t op,
                                             const rpc::XLangCall& call) {
  // op 2: function = class name, args = ctor args -> value.s = instance
  // id. op 3: function = "<iid>:<method>" -> method result. op 4:
  // function = iid.
  rpc::XLangResult result;
  std::vector<rpc::XLangValue> args(call.args().begin(), call.args().end());
  try {
    if (op == 2) {
      auto it = actor_classes_.find(call.function());
      if (it == actor_classes_.end()) {
        result.set_ok(false);
        result.set_error("unknown C++ actor class: " + call.function());
        return result;
      }
      auto inst = std::make_shared<ActorInst>();
      inst->methods = it->second(args);
      std::string iid;
      {
        std::lock_guard<std::mutex> lk(inst_mu_);
        iid = call.function() + "-" + std::to_string(next_iid_++);
        instances_[iid] = inst;
      }
      result.set_ok(true);
      result.mutable_value()->set_s(iid);
      return result;
    }
    if (op == 4) {
      std::lock_guard<std::mutex> lk(inst_mu_);
      instances_.erase(call.function());
      result.set_ok(true);
      return result;
    }
    // op == 3: instance method call, serialized per instance.
    const std::string& target = call.function();
    const size_t sep = target.rfind(':');
    if (sep == std::string::npos) {
      result.set_ok(false);
      result.set_error("malformed actor call target: " + target);
      return result;
    }
    const std::string iid = target.substr(0, sep);
    const std::string method = target.substr(sep + 1);
    std::shared_ptr<ActorInst> inst;
    {
      std::lock_guard<std::mutex> lk(inst_mu_);
      auto it = instances_.find(iid);
      if (it != instances_.end()) inst = it->second;
    }
    if (!inst) {
      result.set_ok(false);
      result.set_error("dead or unknown C++ actor instance: " + iid);
      return result;
    }
    auto mit = inst->methods.find(method);
    if (mit == inst->methods.end()) {
      result.set_ok(false);
      result.set_error("C++ actor has no method: " + method);
      return result;
    }
    std::lock_guard<std::mutex> call_lk(inst->mu);
    *result.mutable_value() = mit->second(args);
    result.set_ok(true);
    return result;
  } catch (const std::exception& e) {
    result.set_ok(false);
    result.set_error(std::string("C++ actor raised: ") + e.what());
    return result;
  } catch (...) {
    result.set_ok(false);
    result.set_error("C++ actor raised a non-standard exception");
    return result;
  }
}

void TaskExecutor::ServeConn(int fd,
                             std::shared_ptr<std::atomic<bool>> done) {
  // Per-request: [u32 len][u8 op][XLangCall] -> [u32 len][u8 ok][XLangResult]
  while (!stopping_.load()) {
    char header[5];
    if (!RecvAllFd(fd, header, 5)) break;
    const uint32_t length = GetU32LE(header);
    std::string body(length, '\0');
    if (length > 0 && !RecvAllFd(fd, &body[0], length)) break;
    rpc::XLangResult result;
    rpc::XLangCall call;
    const uint8_t op = static_cast<uint8_t>(header[4]);
    if ((op < 1 || op > 4) || !call.ParseFromString(body)) {
      result.set_ok(false);
      result.set_error("malformed executor request");
    } else if (op != 1) {
      // 2=CreateActor, 3=ActorCall, 4=KillActor.
      result = HandleActorOp(op, call);
    } else {
      auto it = fns_.find(call.function());
      if (it == fns_.end()) {
        result.set_ok(false);
        result.set_error("unknown C++ function: " + call.function());
      } else {
        std::vector<rpc::XLangValue> args(call.args().begin(),
                                          call.args().end());
        try {
          *result.mutable_value() = it->second(args);
          result.set_ok(true);
        } catch (const std::exception& e) {
          result.set_ok(false);
          result.set_error(std::string("C++ task raised: ") + e.what());
        } catch (...) {
          // A non-std exception escaping would std::terminate the whole
          // worker, killing every other registered function with it.
          result.set_ok(false);
          result.set_error("C++ task raised a non-standard exception");
        }
      }
    }
    const std::string out = result.SerializeAsString();
    char reply_header[5];
    PutU32LE(static_cast<uint32_t>(out.size()), reply_header);
    reply_header[4] = result.ok() ? 1 : 0;
    if (!SendAllFd(fd, reply_header, 5) ||
        !SendAllFd(fd, out.data(), out.size()))
      break;
  }
  ::close(fd);
  done->store(true);
}

}  // namespace ray_tpu

