// ray_tpu C++ worker API.
//
// Reference: the standalone C++ Ray API (cpp/include/ray/api.h in the
// reference tree). This build's runtime is Python+gRPC, so the C++ binding
// point is the framed-protobuf client gateway (ray_tpu/cross_language.py —
// the Ray-Client-server analog): the C++ client submits named cross-language
// functions, puts/gets language-neutral values, and reads the cluster KV,
// all with plain sockets + libprotobuf (no gRPC/pickle dependency).
//
// Usage:
//   ray_tpu::Client c;
//   c.Connect("127.0.0.1", port);
//   auto ref = c.Submit("add", {ray_tpu::V(int64_t(2)), V(int64_t(3))});
//   ray_tpu::rpc::XLangValue out; std::string err;
//   c.Get(ref, &out, &err);   // out.i() == 5

#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "ray_tpu/protobuf/ray_tpu.pb.h"

namespace ray_tpu {

// Convenience constructors for the language-neutral value type.
rpc::XLangValue V(double d);
rpc::XLangValue V(int64_t i);
rpc::XLangValue V(const std::string& s);
rpc::XLangValue VBytes(const std::string& b);
rpc::XLangValue VBool(bool f);

class Client {
 public:
  Client() : fd_(-1) {}
  ~Client();

  // Connect to a ClientGateway (ray_tpu.cross_language.ClientGateway).
  bool Connect(const std::string& host, int port);
  void Close();

  // Object store: put a value, returns the object id ("" on failure).
  std::string Put(const rpc::XLangValue& value);

  // Submit a registered cross-language function; returns the result
  // object id ("" on failure). `resources` uses scheduler names
  // ("CPU", "TPU", custom).
  std::string Submit(const std::string& function,
                     const std::vector<rpc::XLangValue>& args,
                     const std::map<std::string, double>& resources = {});

  // Block until the object is available (gateway-side timeout 120s) and
  // fill `out`. Returns false with `error` set on task failure.
  bool Get(const std::string& object_id, rpc::XLangValue* out,
           std::string* error);

  // Non-blocking readiness probe.
  bool Wait(const std::string& object_id);

  // Release the gateway's pin on an object (call when done with a ref;
  // the gateway also caps held refs with oldest-first eviction).
  bool Free(const std::string& object_id);

  // Cluster KV (reference: ray internal KV).
  bool KvPut(const std::string& ns, const std::string& key,
             const std::string& value);
  bool KvGet(const std::string& ns, const std::string& key,
             std::string* value);

  const std::string& last_error() const { return last_error_; }

 private:
  bool Call(uint8_t op, const std::string& body, std::string* reply);
  bool SendAll(const char* data, size_t n);
  bool RecvAll(char* data, size_t n);

  int fd_;
  std::string last_error_;
};

}  // namespace ray_tpu
