// ray_tpu C++ worker API.
//
// Reference: the standalone C++ Ray API (cpp/include/ray/api.h in the
// reference tree). This build's runtime is Python+gRPC, so the C++ binding
// point is the framed-protobuf client gateway (ray_tpu/cross_language.py —
// the Ray-Client-server analog): the C++ client submits named cross-language
// functions, puts/gets language-neutral values, and reads the cluster KV,
// all with plain sockets + libprotobuf (no gRPC/pickle dependency).
//
// Usage:
//   ray_tpu::Client c;
//   c.Connect("127.0.0.1", port);
//   auto ref = c.Submit("add", {ray_tpu::V(int64_t(2)), V(int64_t(3))});
//   ray_tpu::rpc::XLangValue out; std::string err;
//   c.Get(ref, &out, &err);   // out.i() == 5

#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "ray_tpu/protobuf/ray_tpu.pb.h"

namespace ray_tpu {

// Convenience constructors for the language-neutral value type.
rpc::XLangValue V(double d);
rpc::XLangValue V(int64_t i);
rpc::XLangValue V(const std::string& s);
rpc::XLangValue VBytes(const std::string& b);
rpc::XLangValue VBool(bool f);

class Client {
 public:
  Client() : fd_(-1) {}
  ~Client();

  // Connect to a ClientGateway (ray_tpu.cross_language.ClientGateway).
  bool Connect(const std::string& host, int port);
  void Close();

  // Object store: put a value, returns the object id ("" on failure).
  std::string Put(const rpc::XLangValue& value);

  // Submit a registered cross-language function; returns the result
  // object id ("" on failure). `resources` uses scheduler names
  // ("CPU", "TPU", custom).
  std::string Submit(const std::string& function,
                     const std::vector<rpc::XLangValue>& args,
                     const std::map<std::string, double>& resources = {});

  // Block until the object is available (gateway-side timeout 120s) and
  // fill `out`. Returns false with `error` set on task failure.
  bool Get(const std::string& object_id, rpc::XLangValue* out,
           std::string* error);

  // Non-blocking readiness probe.
  bool Wait(const std::string& object_id);

  // Release the gateway's pin on an object (call when done with a ref;
  // the gateway also caps held refs with oldest-first eviction).
  bool Free(const std::string& object_id);

  // Actors (reference: the Ray Client proxies actor lifecycle for thin
  // clients): create an instance of a registered class, call its
  // methods (returns a result object id), and kill it.
  std::string CreateActor(const std::string& class_name,
                          const std::vector<rpc::XLangValue>& args,
                          const std::map<std::string, double>& resources = {});
  std::string ActorCall(const std::string& actor_id,
                        const std::string& method,
                        const std::vector<rpc::XLangValue>& args);
  bool KillActor(const std::string& actor_id);

  // Cluster KV (reference: ray internal KV).
  bool KvPut(const std::string& ns, const std::string& key,
             const std::string& value);
  bool KvGet(const std::string& ns, const std::string& key,
             std::string* value);

  const std::string& last_error() const { return last_error_; }

  // The local IP this client's socket uses to reach the gateway — the
  // address OTHER cluster nodes can reach this host at (TaskExecutor
  // advertises it; loopback would break cross-node calls).
  std::string LocalAddress() const;

 private:
  bool Call(uint8_t op, const std::string& body, std::string* reply);
  // Shared reply tails: ops returning a GatewayRef / an ok-flag result.
  std::string CallReturningRef(uint8_t op, const std::string& body);
  bool CallReturningOk(uint8_t op, const std::string& body);
  bool SendAll(const char* data, size_t n);
  bool RecvAll(char* data, size_t n);

  int fd_;
  std::string last_error_;
};

// ---------------------------------------------------------------- worker
// C++ worker mode (reference: cpp/src/ray/runtime/task/task_executor.cc —
// C++-defined tasks executed in C++ processes). A TaskExecutor registers
// named functions, serves execution requests over a framed-protobuf
// socket (request [u32 len][u8 op=1][XLangCall], reply
// [u32 len][u8 ok][XLangResult]), and announces each function's address
// in the cluster KV (namespace "__cpp_executors__") through a gateway
// Client — Python callers reach it via cross_language.cpp_function(name),
// and C++ clients via the normal gateway Submit (the gateway routes names
// it finds in that namespace back to this process).
//
// Usage:
//   ray_tpu::TaskExecutor exec;
//   exec.Register("cpp_mul", [](const auto& args) {
//     return ray_tpu::V(args[0].i() * args[1].i());
//   });
//   exec.Serve(gateway_client);    // announce + serve in background
using CppTaskFn = std::function<rpc::XLangValue(
    const std::vector<rpc::XLangValue>&)>;

// A C++ ACTOR instance is its named methods over captured state (the
// factory's closure variables ARE the actor state). Reference:
// cpp/src/ray/runtime/task/task_executor.cc actor dispatch — here state
// lives behind std::function captures instead of member pointers.
using CppActorMethods = std::map<std::string, CppTaskFn>;
using CppActorFactory = std::function<CppActorMethods(
    const std::vector<rpc::XLangValue>&)>;

class TaskExecutor {
 public:
  TaskExecutor() : listen_fd_(-1), port_(0), stopping_(false) {}
  ~TaskExecutor();

  void Register(const std::string& name, CppTaskFn fn);

  // Register an actor CLASS: the factory runs per CreateActor with the
  // constructor args and returns the instance's method table. Announced
  // in KV "__cpp_actor_classes__"; Python reaches it via
  // cross_language.cpp_actor_class(name), C++ clients via the gateway's
  // CreateActor. Method calls on one instance are serialized (ordered
  // actor semantics); distinct instances run concurrently.
  void RegisterActorClass(const std::string& name, CppActorFactory factory);

  // Bind (ephemeral port when 0), announce every registered function via
  // `gateway`, and serve on a background thread. Returns the bound port
  // (0 on failure). An empty advertise_host announces the address this
  // host reaches the gateway from (routable cross-node, unlike loopback).
  int Serve(Client& gateway, const std::string& advertise_host = "",
            int port = 0);

  void Stop();

  int port() const { return port_; }

 private:
  struct Conn {
    std::thread thread;
    int fd;
    std::shared_ptr<std::atomic<bool>> done;
  };

  void AcceptLoop();
  void ServeConn(int fd, std::shared_ptr<std::atomic<bool>> done);
  rpc::XLangResult HandleActorOp(uint8_t op, const rpc::XLangCall& call);

  struct ActorInst {
    CppActorMethods methods;
    std::mutex mu;  // ordered actor semantics per instance
  };

  std::map<std::string, CppTaskFn> fns_;
  std::map<std::string, CppActorFactory> actor_classes_;
  std::map<std::string, std::shared_ptr<ActorInst>> instances_;
  std::mutex inst_mu_;
  uint64_t next_iid_ = 1;  // guarded by inst_mu_
  int listen_fd_;
  int port_;
  std::atomic<bool> stopping_;
  std::thread accept_thread_;
  std::vector<Conn> conns_;  // touched only by accept thread + Stop()
};

}  // namespace ray_tpu
