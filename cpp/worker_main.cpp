// C++ worker example (driven by tests/test_cpp_api.py).
//
// Registers C++-defined tasks with a TaskExecutor, announces them through
// the gateway, and serves until stdin closes. Python callers reach these
// via cross_language.cpp_function("cpp_mul"); C++ clients via the normal
// gateway Submit.

#include <cstdio>
#include <cstdlib>
#include <stdexcept>
#include <string>

#include "ray_tpu/api.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <gateway_port>\n", argv[0]);
    return 2;
  }
  ray_tpu::Client gateway;
  if (!gateway.Connect("127.0.0.1", std::atoi(argv[1]))) {
    std::fprintf(stderr, "connect failed: %s\n",
                 gateway.last_error().c_str());
    return 1;
  }

  ray_tpu::TaskExecutor exec;
  exec.Register("cpp_mul", [](const std::vector<ray_tpu::rpc::XLangValue>&
                                  args) {
    return ray_tpu::V(args.at(0).i() * args.at(1).i());
  });
  exec.Register("cpp_concat",
                [](const std::vector<ray_tpu::rpc::XLangValue>& args) {
                  return ray_tpu::V(args.at(0).s() + args.at(1).s());
                });
  exec.Register("cpp_fail",
                [](const std::vector<ray_tpu::rpc::XLangValue>&)
                    -> ray_tpu::rpc::XLangValue {
                  throw std::runtime_error("intentional c++ failure");
                });
  // C++-defined ACTOR class: state lives in the factory's captures.
  exec.RegisterActorClass(
      "CppCounter",
      [](const std::vector<ray_tpu::rpc::XLangValue>& ctor) {
        auto n = std::make_shared<int64_t>(
            ctor.empty() ? 0 : ctor.at(0).i());
        ray_tpu::CppActorMethods m;
        m["add"] = [n](const std::vector<ray_tpu::rpc::XLangValue>& a) {
          *n += a.at(0).i();
          return ray_tpu::V(*n);
        };
        m["get"] = [n](const std::vector<ray_tpu::rpc::XLangValue>&) {
          return ray_tpu::V(*n);
        };
        m["boom"] = [](const std::vector<ray_tpu::rpc::XLangValue>&)
            -> ray_tpu::rpc::XLangValue {
          throw std::runtime_error("actor method failure");
        };
        return m;
      });
  int port = exec.Serve(gateway);
  if (port == 0) {
    std::fprintf(stderr, "executor serve failed\n");
    return 1;
  }
  std::printf("EXECUTOR_PORT=%d\n", port);
  std::fflush(stdout);
  // Serve until the harness closes stdin (worker-lifetime control).
  std::getchar();
  exec.Stop();
  return 0;
}
