"""Logical-axis sharding rules (GSPMD annotation layer).

The reference expresses DP/FSDP by wrapping modules
(``DistributedDataParallel`` / ``FullyShardedDataParallel`` — reference:
``python/ray/train/torch/train_loop_utils.py:162-201``). TPU-native, the same
strategies are *shardings*, not wrappers: every parameter/activation carries
logical axis names, and a rule table maps logical axes to mesh axes. Swapping
DP → FSDP → TP → any hybrid is a rule-table change; XLA inserts the
all-gathers/reduce-scatters that DDP/FSDP perform by hand.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical activation/parameter axis names used by ray_tpu models.
#   "batch"       – per-example dimension
#   "seq"         – sequence/token dimension (activations)
#   "embed"       – model/hidden dimension
#   "mlp"         – feed-forward intermediate dimension
#   "heads"       – attention heads
#   "kv_heads"    – key/value heads (GQA)
#   "head_dim"    – per-head dimension
#   "vocab"       – vocabulary dimension
#   "kv_seq"      – key/value sequence (ring-attention shifted axis)
#   "experts"     – MoE expert dimension
#   "layers"      – scanned layer dimension (never sharded)

LogicalRules = Tuple[Tuple[str, Union[str, Tuple[str, ...], None]], ...]

# Default rule table: FSDP shards params on the embed dim, TP on heads/mlp/vocab,
# batch over (data, fsdp), sequence over seq. This is the Llama-2-7B
# "FSDP + optional TP" north-star layout (BASELINE.md) expressed as rules.
DEFAULT_RULES: LogicalRules = (
    # parameter axes
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("vocab", "tensor"),
    ("experts", "expert"),
    ("layers", None),
    # activation axes (distinct from param axes: an activation's feature dim
    # stays unsharded on the fsdp axis — fsdp gathers params for compute)
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),
    ("kv_seq", None),
    ("act_embed", None),
    ("act_mlp", "tensor"),
    ("act_heads", "tensor"),
    ("act_kv_heads", "tensor"),
    ("act_vocab", "tensor"),
)


def rules_dict(rules: Optional[LogicalRules] = None) -> Dict[str, Any]:
    return dict(rules if rules is not None else DEFAULT_RULES)


def logical_to_spec(
    logical_axes: Sequence[Optional[str]], rules: Optional[LogicalRules] = None
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec via the rule table."""
    table = rules_dict(rules)
    return P(*[table.get(a) if a is not None else None for a in logical_axes])


def tree_specs(logical_tree: Any, rules: Optional[LogicalRules] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: logical_to_spec(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def tree_shardings(
    mesh: Mesh, logical_tree: Any, rules: Optional[LogicalRules] = None
) -> Any:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), tree_specs(logical_tree, rules)
    )


def constrain(x: Any, mesh: Mesh, *logical_axes: Optional[str],
              rules: Optional[LogicalRules] = None) -> Any:
    """``with_sharding_constraint`` by logical axis names (no-op off-mesh)."""
    if mesh.empty:
        return x
    spec = logical_to_spec(logical_axes, rules)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_tree(tree: Any, shardings: Any) -> Any:
    """Device-put a pytree of arrays onto the given shardings."""
    return jax.tree.map(jax.device_put, tree, shardings)
