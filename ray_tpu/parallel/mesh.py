"""Device-mesh construction and axis conventions for ray_tpu.

This is the TPU-native replacement for the reference's process-group world
(``torch.distributed`` bootstrapped by Ray Train — reference:
``python/ray/train/torch/config.py:153``): instead of ranks + NCCL
communicators, parallelism is expressed as a named :class:`jax.sharding.Mesh`
over the TPU slice, and every collective lowers to XLA ICI/DCN collectives.

Axis conventions (MaxText/t5x-style logical mesh):

===========  =============================================================
axis         meaning
===========  =============================================================
``data``     pure data parallelism (batch sharding, gradients psum)
``fsdp``     ZeRO-3-style parameter/optimizer sharding (also shards batch)
``tensor``   tensor (Megatron-style) model parallelism
``seq``      sequence/context parallelism (ring attention / Ulysses)
``expert``   expert parallelism for MoE dispatch
``stage``    pipeline stages
===========  =============================================================

A mesh does not need every axis: absent axes have size 1 and are dropped.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical mesh-axis order. ICI-heavy axes (tensor/seq) are placed last so
# they land on the innermost (fastest-wraparound, torus-adjacent) dimensions
# of the device array; DCN-friendly axes (data/stage) come first.
MESH_AXES: Tuple[str, ...] = ("stage", "data", "fsdp", "expert", "seq", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. ``-1`` on one axis means "all remaining devices"."""

    data: int = 1
    fsdp: int = -1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    stage: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {
            "stage": self.stage,
            "data": self.data,
            "fsdp": self.fsdp,
            "expert": self.expert,
            "seq": self.seq,
            "tensor": self.tensor,
        }
        wildcard = [k for k, v in sizes.items() if v == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wildcard}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wildcard:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but {n_devices} are available"
            )
        return sizes


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """Build a named Mesh over ``devices`` (default: all global devices).

    Uses :func:`jax.experimental.mesh_utils.create_device_mesh` when all
    global devices are used so the logical mesh is laid out along the physical
    ICI torus (nearest-neighbor collectives stay on-link); otherwise falls
    back to a reshape of the explicit device list.
    """
    config = config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(MESH_AXES)), MESH_AXES)


def mesh_shape(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def batch_axes(mesh: Mesh) -> List[str]:
    """Mesh axes over which the global batch is sharded."""
    return [a for a in ("data", "fsdp") if mesh_shape(mesh).get(a, 1) > 1]


def num_model_replicas(mesh: Mesh) -> int:
    s = mesh_shape(mesh)
    return s.get("data", 1) * s.get("fsdp", 1)
