"""Pipeline parallelism over the ``stage`` mesh axis.

The reference expresses pipeline stages as compiled-DAG nodes with NCCL
channels between GPU actors (SURVEY.md §2.3 aDAG). TPU-native, a pipeline is
ONE jitted SPMD program: layers are sharded onto the ``stage`` mesh axis and
microbatch activations flow between adjacent stages with
``jax.lax.ppermute`` (nearest-neighbor ICI hops) inside a ``lax.scan`` —
GPipe-style fill/drain, no host round-trips per microbatch.

``pipelined`` wraps a per-stage apply function; layers for all stages are
stacked on a leading axis so each stage reads its own slab via shard_map.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.util.jax_compat import shard_map


def pipelined(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis_name: str = "stage",
) -> Callable[[Any, jnp.ndarray], jnp.ndarray]:
    """Build a pipelined forward: y = stageN(...stage1(x)).

    ``stage_fn(stage_params, x_mb)`` applies ONE stage to one microbatch.
    Returned callable takes (stacked_stage_params, batch) where
    ``stacked_stage_params`` has a leading stage axis sharded over
    ``axis_name`` and ``batch`` is [B, ...] with B divisible by
    ``num_microbatches``.

    Schedule: classic GPipe loop of length M + S - 1. At step t, the device
    holding stage s processes microbatch (t - s); activations ppermute one
    hop toward stage s+1 each step. Bubble fraction = (S-1)/(M+S-1).
    """
    num_stages = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def run(stage_params, batch):
        mb = jnp.reshape(batch, (num_microbatches, -1) + batch.shape[1:])

        def body(local_params, mb_local):
            # mb_local: [M, b_local, ...] replicated view per stage device.
            stage_idx = jax.lax.axis_index(axis_name)
            steps = num_microbatches + num_stages - 1
            perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

            local_params = jax.tree.map(lambda p: p[0], local_params)
            out_buf = jnp.zeros_like(mb_local)
            carry = jnp.zeros_like(mb_local[0])

            def step(state, t):
                carry, out_buf = state
                # Stage 0 ingests microbatch t; others use the carried
                # activation that just arrived from the previous stage.
                mb_idx = jnp.clip(t, 0, num_microbatches - 1)
                x_in = jnp.where(stage_idx == 0, mb_local[mb_idx], carry)
                y = stage_fn(local_params, x_in)
                # Valid only while this stage has a real microbatch in hand.
                my_mb = t - stage_idx
                valid = (my_mb >= 0) & (my_mb < num_microbatches)
                y = jnp.where(valid, y, jnp.zeros_like(y))
                # Last stage banks its finished microbatch.
                finished = valid & (stage_idx == num_stages - 1)
                slot = jnp.clip(my_mb, 0, num_microbatches - 1)
                out_buf = jax.lax.cond(
                    finished,
                    lambda b: b.at[slot].set(y),
                    lambda b: b,
                    out_buf)
                # Ship activations one hop down the pipeline.
                carry = jax.lax.ppermute(y, axis_name, perm)
                return (carry, out_buf), None

            (carry, out_buf), _ = jax.lax.scan(
                step, (carry, out_buf), jnp.arange(steps))
            # Only the last stage's buffer is real; psum of the masked buffer
            # replicates it across the stage axis (ppermute cannot broadcast
            # one source to many destinations).
            last = num_stages - 1
            masked = jnp.where(stage_idx == last, out_buf,
                               jnp.zeros_like(out_buf))
            return jax.lax.psum(masked, axis_name)

        spec_params = jax.tree.map(lambda _: P(axis_name), stage_params)
        out = shard_map(
            body, mesh=mesh,
            in_specs=(spec_params, P()),
            out_specs=P(),
            check_vma=False,
        )(stage_params, mb)
        return out.reshape((-1,) + out.shape[2:])

    return run
