"""Mesh / sharding / parallelism primitives (TPU-native core of ray_tpu).

Replaces the reference's NCCL-process-group world view (reference:
``python/ray/util/collective``, ``python/ray/train/torch/config.py``) with
named device meshes + GSPMD sharding rules + XLA collectives.
"""

from ray_tpu.parallel.mesh import (
    MESH_AXES,
    MeshConfig,
    batch_axes,
    make_mesh,
    mesh_shape,
    num_model_replicas,
    single_device_mesh,
)
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    logical_to_spec,
    shard_tree,
    tree_shardings,
    tree_specs,
)

__all__ = [
    "MESH_AXES", "MeshConfig", "batch_axes", "make_mesh", "mesh_shape",
    "num_model_replicas", "single_device_mesh",
    "DEFAULT_RULES", "constrain", "logical_to_spec", "shard_tree",
    "tree_shardings", "tree_specs",
]
