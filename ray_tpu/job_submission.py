"""Job submission: run driver scripts against a cluster.

Reference: ``python/ray/dashboard/modules/job`` — ``JobManager``
(job_manager.py:59) launches each job's entrypoint as a supervised
subprocess, tracks status + logs, and exposes a client
(``JobSubmissionClient``). Here job metadata lives in the GCS KV store
(namespace "job"), so any client connected to the cluster sees the same job
table; the entrypoint subprocess gets ``RAY_TPU_ADDRESS`` so its
``ray_tpu.init()`` joins the cluster.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu.protobuf import ray_tpu_pb2 as pb

KV_NS = "job"
# The supervisor thread refreshes the job record's heartbeat at this
# cadence while the entrypoint runs; the GCS job reconciler marks records
# FAILED once the heartbeat lapses past its TTL (a dead client can never
# finalize its own jobs — gcs/server.py::_reconcile_jobs).
HEARTBEAT_PERIOD_S = 2.0


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address``: the cluster GCS address (host:port)."""
        self.address = address
        self.gcs = rpc.get_stub("GcsService", address)
        self._procs: Dict[str, subprocess.Popen] = {}
        # Jobs this client stopped: the supervisor must neither heartbeat
        # them (a load→save racing stop_job could resurrect RUNNING over
        # STOPPED) nor finalize them as FAILED on the kill's exit code.
        self._stopped: set = set()

    # ------------------------------------------------------------- kv helpers
    def _save(self, job_id: str, info: Dict[str, Any]):
        self.gcs.KvPut(pb.KvRequest(ns=KV_NS, key=job_id,
                                    value=json.dumps(info).encode(),
                                    overwrite=True))

    def _load(self, job_id: str) -> Optional[Dict[str, Any]]:
        reply = self.gcs.KvGet(pb.KvRequest(ns=KV_NS, key=job_id))
        if not reply.found:
            return None
        return json.loads(reply.value)

    # ------------------------------------------------------------- public api
    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        job_id = submission_id or f"raytpu_job_{uuid.uuid4().hex[:10]}"
        logdir = os.path.join("/tmp", "ray_tpu_jobs", job_id)
        os.makedirs(logdir, exist_ok=True)
        info = {
            "job_id": job_id, "entrypoint": entrypoint,
            "status": JobStatus.PENDING, "metadata": metadata or {},
            "start_time": time.time(), "end_time": None,
            "heartbeat_time": time.time(),
            "log_path": os.path.join(logdir, "driver.log"),
        }
        self._save(job_id, info)

        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.address
        env.update((runtime_env or {}).get("env_vars", {}))
        if "working_dir" in (runtime_env or {}):
            cwd = runtime_env["working_dir"]
        else:
            cwd = os.getcwd()
        log_f = open(info["log_path"], "wb")
        proc = subprocess.Popen(entrypoint, shell=True, cwd=cwd, env=env,
                                stdout=log_f, stderr=subprocess.STDOUT)
        self._procs[job_id] = proc
        info["status"] = JobStatus.RUNNING
        info["pid"] = proc.pid
        self._save(job_id, info)
        threading.Thread(target=self._supervise, args=(job_id, proc),
                         daemon=True).start()
        return job_id

    def _supervise(self, job_id: str, proc: subprocess.Popen):
        # Poll (don't block in wait()): the record's heartbeat must keep
        # refreshing or the GCS reconciler would sweep a healthy long job.
        last_beat = 0.0
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            now = time.monotonic()
            if now - last_beat >= HEARTBEAT_PERIOD_S:
                last_beat = now
                try:
                    if job_id not in self._stopped:
                        info = self._load(job_id) or {}
                        status = info.get("status")
                        if status == JobStatus.FAILED and \
                                "client died" in str(info.get("message")):
                            # The reconciler false-positived (GCS outage
                            # outlived the TTL): the entrypoint is alive
                            # — this beat proves it — so take the record
                            # back.
                            info["status"] = JobStatus.RUNNING
                            info.pop("end_time", None)
                            info.pop("message", None)
                            status = JobStatus.RUNNING
                        if status == JobStatus.RUNNING:
                            info["heartbeat_time"] = time.time()
                            self._save(job_id, info)
                except Exception:  # noqa: BLE001 — GCS briefly unreachable
                    pass
            time.sleep(0.25)
        info = self._load(job_id) or {}
        if job_id in self._stopped:
            # stop_job finalized the record; re-assert STOPPED in case a
            # racing heartbeat save clobbered it with a stale RUNNING.
            if info.get("status") != JobStatus.STOPPED:
                info["status"] = JobStatus.STOPPED
                info.setdefault("end_time", time.time())
                self._save(job_id, info)
            return
        if info.get("status") == JobStatus.STOPPED:
            return  # stop_job already finalized the record
        info["status"] = JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED
        info["end_time"] = time.time()
        info["return_code"] = rc
        self._save(job_id, info)

    def get_job_status(self, job_id: str) -> str:
        info = self._load(job_id)
        if info is None:
            raise ValueError(f"unknown job {job_id!r}")
        return info["status"]

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        info = self._load(job_id)
        if info is None:
            raise ValueError(f"unknown job {job_id!r}")
        return info

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        try:
            with open(info["log_path"]) as f:
                return f.read()
        except OSError:
            return ""

    def list_jobs(self) -> List[Dict[str, Any]]:
        reply = self.gcs.KvKeys(pb.KvRequest(ns=KV_NS, prefix=""))
        return [self._load(k) for k in reply.keys]

    def stop_job(self, job_id: str) -> bool:
        proc = self._procs.get(job_id)
        if proc is not None and proc.poll() is None:
            self._stopped.add(job_id)
            proc.terminate()
            info = self._load(job_id) or {}
            info["status"] = JobStatus.STOPPED
            info["end_time"] = time.time()
            self._save(job_id, info)
            return True
        return False

    def wait_until_finished(self, job_id: str, timeout_s: float = 300.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} still {status} after {timeout_s}s")
