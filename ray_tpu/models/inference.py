"""Llama inference: KV-cache prefill + single-token decode, jit-compiled.

The reference serves LLMs by hosting vLLM (``python/ray/llm/_internal/serve``
— SURVEY.md §2.4); ray_tpu serves its own models natively. TPU-shaped
decisions:

* the KV cache is a static-shape ring of ``[L, B, S_max, KVH, D]`` arrays —
  no dynamic shapes ever reach XLA; position masking handles partial fill;
* prefill processes the whole (padded) prompt in one batched pass (MXU
  utilization) and decode is one jitted step with donated cache buffers (no
  HBM churn);
* cache layout is shardable with the same logical-axis rules as training
  (batch on data axes, heads on tensor) so a TP-sharded server is a rule
  change, not new code.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu._private import xla_monitor
from ray_tpu.models import llama
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S_max, KVH, D]
    v: jnp.ndarray

    @classmethod
    def create(cls, config: llama.LlamaConfig, batch_size: int,
               max_len: int) -> "KVCache":
        shape = (config.num_layers, batch_size, max_len,
                 config.num_kv_heads, config.head_dim)
        return cls(k=jnp.zeros(shape, config.dtype),
                   v=jnp.zeros(shape, config.dtype))


class SelfDrafter:
    """Speculative-decode drafter that IS the target model, truncated: the
    first ``draft_layers`` decoder layers plus the target's own final norm
    and lm_head (:func:`llama.truncated`). Because those layers compute
    bitwise the same K/V the target writes, the drafter reads and writes
    the target's paged arena directly (layers [0:n)) — context K/V is
    already resident, draft writes land where verify will rewrite the
    identical bytes, and no second checkpoint or draft arena exists.

    ``draft_layers=None`` defers to the engine default
    (``RAY_TPU_SPEC_DRAFT_LAYERS``, else num_layers // 4)."""

    external = False

    def __init__(self, draft_layers: Optional[int] = None):
        self.draft_layers = draft_layers


class ExternalLlamaDrafter:
    """Speculative-decode drafter backed by a separate (small) Llama
    checkpoint sharing the target's vocabulary. Keeps its own dense
    per-slot KV cache (``KVCache``), filled by a draft prefill of the full
    prompt at admission and advanced by the spec tick's draft steps; the
    engine's rewind (host-count re-upload) needs no drafter cooperation
    because stale entries past the committed length are overwritten before
    they are ever attended."""

    external = True

    def __init__(self, config: llama.LlamaConfig, params=None,
                 seed: int = 0):
        self.config = config
        self.params = params if params is not None else llama.init_params(
            config, jax.random.PRNGKey(seed))


def _attend_cached(q, cache_k, cache_v, q_positions, scale):
    """q: [B, S, H, D] at absolute positions; cache: [B, S_max, KVH, D].

    Causal masking is positional: query at position p sees cache slots
    [0..p]. Unfilled slots are masked out by the same rule.
    """
    b, s, hq, d = q.shape
    s_max, hkv = cache_k.shape[1], cache_k.shape[2]
    group = hq // hkv
    qg = q.reshape(b, s, hkv, group, d).astype(jnp.float32)
    logits = jnp.einsum("bqhgd,bkhd->bqhgk", qg,
                        cache_k.astype(jnp.float32)) * scale
    slots = jnp.arange(s_max)
    mask = q_positions[:, None] >= slots[None, :]           # [S, S_max]
    logits = jnp.where(mask[None, :, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bqhgk,bkhd->bqhgd", probs,
                     cache_v.astype(jnp.float32))
    return out.reshape(b, s, hq, d).astype(q.dtype)


def _block(x, layer, cache_k, cache_v, positions, cos, sin, c):
    """One decoder layer over tokens at ``positions``, updating the cache."""
    scale = c.head_dim ** -0.5
    h = rms_norm(x, layer["attn_norm"], c.rms_eps)
    q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(c.dtype))
    k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(c.dtype))
    v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(c.dtype))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    # Scatter new K/V into the cache at their absolute positions.
    cache_k = jax.lax.dynamic_update_slice(
        cache_k, k.astype(cache_k.dtype), (0, positions[0], 0, 0))
    cache_v = jax.lax.dynamic_update_slice(
        cache_v, v.astype(cache_v.dtype), (0, positions[0], 0, 0))
    o = _attend_cached(q, cache_k, cache_v, positions, scale)
    x = x + jnp.einsum("bshd,hde->bse", o, layer["wo"].astype(c.dtype))
    h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(c.dtype))
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(c.dtype))
    x = x + jnp.einsum("bsm,me->bse", jax.nn.silu(gate) * up,
                       layer["w_down"].astype(c.dtype))
    return x, cache_k, cache_v


def lm_head_logits(x, params, config: llama.LlamaConfig):
    """Final-norm hidden states [B, S, E] -> fp32 logits [B, S, V].

    The projection runs in the params' storage dtype (bf16 on TPU) with
    fp32 MXU accumulation (``preferred_element_type``) instead of
    materializing an fp32 upcast of the lm_head — at decode batch sizes
    the head read dominates the tick's non-KV bytes, so this halves it.
    Greedy argmax over the result must stay bit-stable vs the fp32 path
    (tests/test_continuous_batching.py::test_bf16_lm_head_argmax_parity).
    """
    c = config
    return jnp.einsum("bse,ev->bsv", x.astype(c.dtype),
                      params["lm_head"].astype(c.dtype),
                      preferred_element_type=jnp.float32)


def _forward_cached(params, tokens, positions, cache: KVCache,
                    config: llama.LlamaConfig):
    """tokens [B, S] at absolute ``positions`` [S]; returns (logits, cache)."""
    c = config
    cos, sin = rope_frequencies(c.head_dim, tokens.shape[1], c.rope_theta,
                                positions=positions)
    x = params["embed"].astype(c.dtype)[tokens]

    def layer_fn(carry, inputs):
        x = carry
        layer, ck, cv = inputs
        x, ck, cv = _block(x, layer, ck, cv, positions, cos, sin, c)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer_fn, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    logits = lm_head_logits(x, params, c)
    return logits, KVCache(k=new_k, v=new_v)


class LlamaGenerator:
    """Compiled prefill + decode loops for one model instance."""

    def __init__(self, config: llama.LlamaConfig, params=None,
                 max_len: int = 512, seed: int = 0):
        self.config = config
        self.max_len = max_len
        self.params = params if params is not None else llama.init_params(
            config, jax.random.PRNGKey(seed))

        cfg = config

        # Whole-prompt prefill legitimately compiles once per distinct
        # prompt length (the batch generate API pads nothing); the
        # production serving path is the bucketed engine, so this one is
        # compile-tracked but exempt from retrace flagging.
        @xla_monitor.instrument(name="llama_prefill", shape_policy="free")
        def prefill(params, tokens, cache):
            positions = jnp.arange(tokens.shape[1])
            return _forward_cached(params, tokens, positions, cache, cfg)

        @xla_monitor.instrument(name="llama_decode", donate_argnums=(2,))
        def decode(params, token, cache, pos):
            positions = jnp.asarray([pos])
            logits, cache = _forward_cached(
                params, token[:, None], positions, cache, cfg)
            return logits[:, -1], cache

        self._prefill = prefill
        self._decode = decode

    def generate(self, prompt_tokens, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0):
        """prompt_tokens: [B, P] int32. Returns [B, max_new_tokens]."""
        tokens = jnp.asarray(prompt_tokens, jnp.int32)
        b, p = tokens.shape
        assert p + max_new_tokens <= self.max_len
        cache = KVCache.create(self.config, b, self.max_len)
        logits, cache = self._prefill(self.params, tokens, cache)
        last = logits[:, p - 1]
        key = jax.random.PRNGKey(seed)
        out = []
        pos = p
        for _ in range(max_new_tokens):
            if temperature > 0:
                key, sub = jax.random.split(key)
                nxt = jax.random.categorical(sub, last / temperature, axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            nxt = nxt.astype(jnp.int32)
            out.append(nxt)
            last, cache = self._decode(self.params, nxt, cache, pos)
            pos += 1
        return jnp.stack(out, axis=1)
