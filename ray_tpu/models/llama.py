"""Flagship model: Llama-family decoder, pure JAX, GSPMD-sharded.

This is the BASELINE.md north-star workload (Llama-2-7B fine-tune on a TPU
pod). Where the reference framework hosts external engines for the model
itself (SURVEY.md §2.3 — TP/PP arrive via vLLM / HF integrations), ray_tpu
ships the model natively, TPU-first:

* parameters are plain pytrees with a parallel pytree of *logical axis
  names*; :mod:`ray_tpu.parallel.sharding` rules map them onto any mesh
  (DP / FSDP / TP / SP hybrids are rule-table changes, not model changes);
* the layer stack is a ``jax.lax.scan`` over stacked layer params (one
  compiled layer body regardless of depth) with optional ``jax.checkpoint``
  rematerialization;
* attention auto-selects: pallas flash attention on a local sequence, ring
  attention over the ``seq`` mesh axis when the sequence is context-parallel;
* activations/params default to bfloat16 with fp32 logits/loss.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.util import jax_compat as _jax_compat  # noqa: F401 - pins
# partitionable threefry BEFORE any param init traces: sharded init must
# produce the same values on every mesh layout (see jax_compat docstring).
from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.parallel import constrain, mesh_shape

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # remat_policy: "full" recomputes the whole layer body in the backward
    # (the measured-best default at the bench shape); "attn_out" saves the
    # attention outputs only; "mlp_only" additionally saves q/k/v (the
    # least recompute, the most memory). See forward() for the exact
    # save-lists and measured tradeoffs.
    remat_policy: str = "full"
    # attention: "auto" | "flash" | "ring" | "reference"
    attention: str = "auto"

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        return LlamaConfig(hidden_size=5120, intermediate_size=13824,
                           num_layers=40, num_heads=40, num_kv_heads=40, **kw)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, hidden_size=4096,
                           intermediate_size=14336, num_layers=32,
                           num_heads=32, num_kv_heads=8,
                           rope_theta=500000.0, max_seq_len=8192, **kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """CPU-runnable config for tests (BASELINE.md config #1 analog)."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("remat", False)
        return LlamaConfig(**kw)


def logical_axes(config: LlamaConfig) -> Params:
    """Pytree of logical-axis tuples matching :func:`init_params`."""
    layer = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    return {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(config: LlamaConfig, key: jax.Array) -> Params:
    """Random init (normal / scaled), stacked over layers for lax.scan."""
    c = config
    k_embed, k_head, k_layers = jax.random.split(key, 3)

    def norm_init(*shape):
        return jnp.ones(shape, c.dtype)

    def dense_init(key, *shape, scale=None):
        fan_in = shape[0] if len(shape) == 2 else int(jnp.prod(jnp.array(shape[:-1])))
        scale = scale if scale is not None else fan_in ** -0.5
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(c.dtype)

    keys = jax.random.split(k_layers, 7)
    L, E, M = c.num_layers, c.hidden_size, c.intermediate_size
    H, KV, D = c.num_heads, c.num_kv_heads, c.head_dim

    def stacked(key, fan_in, *shape):
        scale = fan_in ** -0.5
        out = jax.random.normal(key, (L,) + shape, jnp.float32) * scale
        return out.astype(c.dtype)

    layers = {
        "attn_norm": jnp.ones((L, E), c.dtype),
        "wq": stacked(keys[0], E, E, H, D),
        "wk": stacked(keys[1], E, E, KV, D),
        "wv": stacked(keys[2], E, E, KV, D),
        "wo": stacked(keys[3], H * D, H, D, E),
        "mlp_norm": jnp.ones((L, E), c.dtype),
        "w_gate": stacked(keys[4], E, E, M),
        "w_up": stacked(keys[5], E, E, M),
        "w_down": stacked(keys[6], M, M, E),
    }
    return {
        "embed": dense_init(k_embed, c.vocab_size, E, scale=1.0),
        "layers": layers,
        "final_norm": jnp.ones((E,), c.dtype),
        "lm_head": dense_init(k_head, E, c.vocab_size),
    }


def truncated(config: LlamaConfig, params: Params,
              num_layers: int) -> Tuple[LlamaConfig, Params]:
    """First-``num_layers`` view of a model: (config, params) where the
    layer stack is sliced to the leading ``num_layers`` and the embedding,
    final norm, and lm_head are shared (same arrays, zero copies).

    This is the speculative-decode self-drafter (EAGLE/Medusa-style
    truncated-depth draft): because the sliced stack computes bitwise the
    SAME layer-0..n-1 activations and K/V as the full model, the drafter
    can read and write the target's own paged KV arena for those layers —
    no second checkpoint, no separate draft arena."""
    if not 1 <= num_layers <= config.num_layers:
        raise ValueError(
            f"truncated depth must be in [1, {config.num_layers}], "
            f"got {num_layers}")
    cfg = dataclasses.replace(config, num_layers=num_layers)
    sliced = dict(params)
    sliced["layers"] = jax.tree.map(lambda a: a[:num_layers],
                                    params["layers"])
    return cfg, sliced


def _select_attention(config: LlamaConfig, mesh: Optional[Mesh]):
    mode = config.attention
    if mode == "auto":
        if mesh is not None and not mesh.empty and mesh_shape(mesh).get("seq", 1) > 1:
            mode = "ring"
        else:
            mode = "flash"
    return mode


def _attend(q, k, v, config: LlamaConfig, mesh: Optional[Mesh]):
    mode = _select_attention(config, mesh)
    if mode == "reference":
        return mha_reference(q, k, v, causal=True)
    if mode == "ring":
        from jax.sharding import PartitionSpec as P

        from ray_tpu.util.jax_compat import shard_map

        qspec = P(("data", "fsdp"), "seq", "tensor", None)
        kvspec = P(("data", "fsdp"), "seq", "tensor", None)
        fn = shard_map(
            functools.partial(ring_attention, axis_name="seq", causal=True),
            mesh=mesh,
            in_specs=(qspec, kvspec, kvspec),
            out_specs=qspec,
            check_vma=False,
        )
        return fn(q, k, v)
    return flash_attention(q, k, v, causal=True)


def forward(
    params: Params,
    tokens: jnp.ndarray,
    config: LlamaConfig,
    mesh: Optional[Mesh] = None,
    return_hidden: bool = False,
    mlp_fn=None,
):
    """Compute logits [B, S, V] (fp32) for int32 tokens [B, S].

    ``mlp_fn(h, layer) -> (out, aux_scalar)`` swaps the dense SwiGLU block
    for another token-mixing-free sublayer — the MoE family
    (:mod:`ray_tpu.models.mixtral`) routes through here so the attention
    backbone, remat policy, and sharding constraints are shared, not
    copied. With ``return_hidden=True`` the return value is the tuple
    ``(hidden [B, S, E], aux_total)`` where ``aux_total`` is the per-layer
    auxiliary scalar (router load-balancing loss) summed over layers;
    otherwise just the logits array.
    """
    c = config
    seq_len = tokens.shape[1]
    cos, sin = rope_frequencies(c.head_dim, seq_len, c.rope_theta)

    x = params["embed"].astype(c.dtype)[tokens]
    x = constrain(x, mesh, "batch", "seq", "act_embed") if mesh is not None else x

    from jax.ad_checkpoint import checkpoint_name

    def dense_mlp(h, layer):
        gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(c.dtype))
        up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(c.dtype))
        act = jax.nn.silu(gate) * up
        if mesh is not None:
            act = constrain(act, mesh, "batch", "seq", "act_mlp")
        down = jnp.einsum("bsm,me->bse", act, layer["w_down"].astype(c.dtype))
        return down, jnp.zeros((), jnp.float32)

    mlp = mlp_fn or dense_mlp

    def layer_fn(carry, layer):
        x, aux_sum = carry
        h = rms_norm(x, layer["attn_norm"], c.rms_eps)
        q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(c.dtype))
        k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(c.dtype))
        v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(c.dtype))
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if mesh is not None:
            q = constrain(q, mesh, "batch", "seq", "act_heads", None)
            k = constrain(k, mesh, "batch", "seq", "act_kv_heads", None)
            v = constrain(v, mesh, "batch", "seq", "act_kv_heads", None)
        q = checkpoint_name(q, "q")
        k = checkpoint_name(k, "k")
        v = checkpoint_name(v, "v")
        o = _attend(q, k, v, c, mesh)
        o = checkpoint_name(o, "attn_out")
        o = jnp.einsum("bshd,hde->bse", o, layer["wo"].astype(c.dtype))
        x = x + o
        if mesh is not None:
            x = constrain(x, mesh, "batch", "seq", "act_embed")

        h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
        down, aux = mlp(h, layer)
        x = x + down
        if mesh is not None:
            x = constrain(x, mesh, "batch", "seq", "act_embed")
        return (x, aux_sum + aux), None

    body = layer_fn
    if c.remat:
        if c.remat_policy == "mlp_only":
            policy = jax.checkpoint_policies.save_only_these_names(
                "q", "k", "v", "attn_out"
            )
        elif c.remat_policy == "attn_out":
            # Save ONLY the attention outputs (~33MB/layer at the bench
            # shape). NOTE: flash_attention is a custom_vjp whose bwd
            # needs (q, k, v, out, lse) residuals, so the remat backward
            # STILL replays the flash forward — this only spares the
            # wo-projection backward's input recompute. Measured slightly
            # WORSE than "full" on v5e at the bench shape; kept as a
            # tuning point for other shapes.
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out")
        elif c.remat_policy == "full":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        else:
            raise ValueError(
                f"unknown remat_policy {c.remat_policy!r}; "
                "expected 'full', 'attn_out', or 'mlp_only'"
            )
        body = jax.checkpoint(layer_fn, policy=policy)
    (x, aux_total), _ = jax.lax.scan(
        lambda carry, lp: body(carry, lp),
        (x, jnp.zeros((), jnp.float32)), params["layers"])

    x = rms_norm(x, params["final_norm"], c.rms_eps)
    if return_hidden:
        return x, aux_total
    # bf16 operands with fp32 accumulation: the params are STORED bf16, so
    # upcasting inputs to fp32 buys no precision on the products — it only
    # runs the MXU at its fp32 rate (~4x slower on v5e). fp32 accumulate +
    # fp32 logits keep the softmax math exact.
    logits = jnp.einsum(
        "bse,ev->bsv", x, params["lm_head"].astype(c.dtype),
        preferred_element_type=jnp.float32,
    )
    if mesh is not None:
        logits = constrain(logits, mesh, "batch", "seq", "act_vocab")
    return logits


def hidden_states(
    params: Params,
    tokens: jnp.ndarray,
    config: LlamaConfig,
    mesh: Optional[Mesh] = None,
    mlp_fn=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(final-norm hidden states [B, S, E], summed aux scalar)."""
    return forward(params, tokens, config, mesh, return_hidden=True,
                   mlp_fn=mlp_fn)


def loss_fn(
    params: Params,
    batch: Dict[str, jnp.ndarray],
    config: LlamaConfig,
    mesh: Optional[Mesh] = None,
    vocab_chunks: int = 8,
    mlp_fn=None,
    aux_coeff: float = 0.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Next-token cross-entropy. batch: {"tokens": [B,S] int32, "mask": [B,S]}.

    The LM-head matmul + softmax run over *sequence chunks* so the fp32
    [B, S, V] logits tensor is never materialized (V=32k dominates HBM at
    long seq) — the standard memory-side optimization for LLM training on
    16GB-HBM chips; remat recomputes each chunk's logits in the backward.

    ``mlp_fn``/``aux_coeff`` support MoE variants: the per-layer auxiliary
    scalar (router load balancing) is summed by the backbone and added to
    the loss with weight ``aux_coeff``.
    """
    tokens = batch["tokens"]
    mask = batch.get("mask")
    x, aux = hidden_states(params, tokens, config, mesh,
                           mlp_fn=mlp_fn)                # [B, S, E]
    targets = tokens[:, 1:]
    x = x[:, :-1]
    m = (mask[:, 1:] if mask is not None else
         jnp.ones_like(targets)).astype(jnp.float32)
    # Keep the head in the params' storage dtype: the chunk matmul runs
    # bf16 x bf16 -> fp32-accumulated logits (see forward()).
    head = params["lm_head"].astype(config.dtype)

    s = x.shape[1]
    n_chunks = vocab_chunks
    while s % n_chunks:
        n_chunks -= 1
    xs = x.reshape(x.shape[0], n_chunks, s // n_chunks, x.shape[2])
    ts = targets.reshape(targets.shape[0], n_chunks, s // n_chunks)
    ms = m.reshape(m.shape[0], n_chunks, s // n_chunks)

    @functools.partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def chunk_stats(xc, tc, mc):
        logits = jnp.einsum("bse,ev->bsv", xc, head,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        nll = (lse - picked) * mc
        correct = (jnp.argmax(logits, -1) == tc) * mc
        return jnp.sum(nll), jnp.sum(correct)

    def scan_body(carry, inp):
        xc, tc, mc = inp
        nll, correct = chunk_stats(xc, tc, mc)
        return (carry[0] + nll, carry[1] + correct), None

    (nll_sum, correct_sum), _ = jax.lax.scan(
        scan_body, (jnp.zeros(()), jnp.zeros(())),
        (xs.transpose(1, 0, 2, 3), ts.transpose(1, 0, 2),
         ms.transpose(1, 0, 2)))
    total = jnp.maximum(jnp.sum(m), 1.0)
    loss = nll_sum / total
    acc = correct_sum / total
    metrics = {"loss": loss, "accuracy": acc, "tokens": total}
    if aux_coeff:
        metrics["aux_loss"] = aux
        loss = loss + aux_coeff * aux
        metrics["total_loss"] = loss
    return loss, metrics


def num_params(config: LlamaConfig) -> int:
    c = config
    per_layer = (
        2 * c.hidden_size
        + c.hidden_size * c.num_heads * c.head_dim * 2
        + c.hidden_size * c.num_kv_heads * c.head_dim * 2
        + 3 * c.hidden_size * c.intermediate_size
    )
    return (
        c.vocab_size * c.hidden_size * 2
        + c.hidden_size
        + c.num_layers * per_layer
    )
