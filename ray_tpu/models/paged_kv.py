"""Paged KV cache: a shared block arena + host-side block allocator.

The dense pooled cache (``inference.KVCache``) gives every slot a
private ``[S_max]`` stripe, so every decode tick streams ``S_max``
entries per slot regardless of how many are live — at 32 slots x 512
max_len with ~40-token requests that is >10x pure padding traffic. The
paged layout mirrors vLLM's KV manager: one arena of fixed-size blocks
(``[L, num_blocks, block_size, KVH, D]``) shared by all slots, a
per-slot block table naming the blocks it filled, and a free-list
allocator on the host. A slot's attention reads only its live blocks;
freeing a slot returns its blocks for immediate reuse; and block
granularity is the unit future prefix/radix sharing needs (ROADMAP
item 2).

Optional int8 quantization stores the arena as int8 with fp32
per-token/per-kv-head scales in block-shaped sidecars — block-local
scale state that travels with its block through the same table
indirection (``RAY_TPU_KV_DTYPE=int8`` or the engine's ``kv_dtype``
knob). Block 0 is a reserved GARBAGE block: freed slots' masked lanes
keep scattering somewhere harmless without branching in the tick.
"""

from __future__ import annotations

import os
from typing import List, NamedTuple, Optional

import jax.numpy as jnp

from ray_tpu.models import llama

GARBAGE_BLOCK = 0

KV_DTYPES = ("bf16", "int8")


def resolve_kv_dtype(kv_dtype: Optional[str]) -> str:
    """Explicit arg > ``RAY_TPU_KV_DTYPE`` env > bf16 (storage parity
    with the dense cache)."""
    if kv_dtype is None:
        kv_dtype = os.environ.get("RAY_TPU_KV_DTYPE", "").strip().lower() \
            or "bf16"
    kv_dtype = str(kv_dtype).lower()
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype {kv_dtype!r} not supported (one of {KV_DTYPES})")
    return kv_dtype


def quantize_kv(x):
    """Symmetric per-token/per-kv-head int8: x [..., H, D] -> (int8 same
    shape, fp32 scales [..., H]). Zero vectors quantize to zeros with a
    zero scale (dequantizing back to exact zeros)."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)                  # [..., H]
    scale = amax / 127.0
    q = jnp.round(x / jnp.where(scale == 0.0, 1.0, scale)[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


class PagedKVCache(NamedTuple):
    """KV arena: k/v ``[L, NB, bs, KVH, D]``; scales ``[L, NB, bs, KVH]``
    fp32 when the arena is int8, else None."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @classmethod
    def create(cls, config: llama.LlamaConfig, num_blocks: int,
               block_size: int, kv_dtype: str = "bf16") -> "PagedKVCache":
        kv_dtype = resolve_kv_dtype(kv_dtype)
        shape = (config.num_layers, num_blocks, block_size,
                 config.num_kv_heads, config.head_dim)
        if kv_dtype == "int8":
            return cls(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       k_scale=jnp.zeros(shape[:-1], jnp.float32),
                       v_scale=jnp.zeros(shape[:-1], jnp.float32))
        return cls(k=jnp.zeros(shape, config.dtype),
                   v=jnp.zeros(shape, config.dtype))

    def token_bytes(self) -> int:
        """Arena bytes one live token occupies across all layers (the
        live-traffic estimate the achieved-bandwidth gauges use)."""
        layers, _, _, kvh, d = self.k.shape
        n = 2 * layers * kvh * d * jnp.dtype(self.k.dtype).itemsize
        if self.k_scale is not None:
            n += 2 * layers * kvh * 4
        return n


class BlockAllocator:
    """Host-side free-list over arena block ids. Block 0 (GARBAGE_BLOCK)
    is never handed out: freed slots keep scattering their masked-lane
    garbage there. LIFO reuse keeps hot blocks hot."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("paged arena needs >= 2 blocks "
                             "(block 0 is reserved)")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set = set()   # O(1) double-free detection

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._allocated)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None (all-or-nothing) when the arena can't cover
        them — the caller leaves the request queued."""
        if n <= 0:
            return []      # [-0:] would slice (and drain) the whole list
        if n > len(self._free):
            return None
        taken = self._free[-n:][::-1]
        del self._free[-n:]
        self._allocated.update(taken)
        return taken

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise ValueError("cannot free the reserved garbage block")
            if b not in self._allocated:
                raise ValueError(f"double free / bad block id {b}")
        self._allocated.difference_update(blocks)
        self._free.extend(reversed(blocks))

    def reset(self) -> None:
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._allocated.clear()
