"""Paged KV cache: a shared block arena + host-side block allocator.

The dense pooled cache (``inference.KVCache``) gives every slot a
private ``[S_max]`` stripe, so every decode tick streams ``S_max``
entries per slot regardless of how many are live — at 32 slots x 512
max_len with ~40-token requests that is >10x pure padding traffic. The
paged layout mirrors vLLM's KV manager: one arena of fixed-size blocks
(``[L, num_blocks, block_size, KVH, D]``) shared by all slots, a
per-slot block table naming the blocks it filled, and a free-list
allocator on the host. A slot's attention reads only its live blocks;
freeing a slot returns its blocks for immediate reuse; and block
granularity is the unit future prefix/radix sharing needs (ROADMAP
item 2).

Optional int8 quantization stores the arena as int8 with fp32
per-token/per-kv-head scales in block-shaped sidecars — block-local
scale state that travels with its block through the same table
indirection (``RAY_TPU_KV_DTYPE=int8`` or the engine's ``kv_dtype``
knob). Block 0 is a reserved GARBAGE block: freed slots' masked lanes
keep scattering somewhere harmless without branching in the tick.

CROSS-REQUEST PREFIX REUSE (ROADMAP item 2, SGLang RadixAttention /
vLLM automatic-prefix-caching analog): :class:`RadixBlockIndex` maps
block-aligned token-id chunks to the arena blocks already holding their
K/V, so a chat fleet's shared system prompts prefill once per replica
and every later request splices the cached blocks into its table
read-only. A block is then in one of three states:

* **free** — on the :class:`BlockAllocator` free list;
* **live** — referenced by ≥1 slot; indexed blocks carry a per-node
  refcount (two requests sharing a system prompt both pin its blocks)
  and are NEVER reclaimed while any reference is live;
* **cached** — refcount dropped to 0 on slot release, but the block is
  parked in the index's LRU instead of freed: a later prefix match
  revives it for free, and arena pressure reclaims it (leaf-first,
  oldest-first) before admission ever blocks on the arena.
"""

from __future__ import annotations

import os
from collections import OrderedDict, deque
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp

from ray_tpu.models import llama

GARBAGE_BLOCK = 0

KV_DTYPES = ("bf16", "int8")


def resolve_kv_dtype(kv_dtype: Optional[str]) -> str:
    """Explicit arg > ``RAY_TPU_KV_DTYPE`` env > bf16 (storage parity
    with the dense cache)."""
    if kv_dtype is None:
        kv_dtype = os.environ.get("RAY_TPU_KV_DTYPE", "").strip().lower() \
            or "bf16"
    kv_dtype = str(kv_dtype).lower()
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype {kv_dtype!r} not supported (one of {KV_DTYPES})")
    return kv_dtype


def quantize_kv(x):
    """Symmetric per-token/per-kv-head int8: x [..., H, D] -> (int8 same
    shape, fp32 scales [..., H]). Zero vectors quantize to zeros with a
    zero scale (dequantizing back to exact zeros)."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)                  # [..., H]
    scale = amax / 127.0
    q = jnp.round(x / jnp.where(scale == 0.0, 1.0, scale)[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


class PagedKVCache(NamedTuple):
    """KV arena: k/v ``[L, NB, bs, KVH, D]``; scales ``[L, NB, bs, KVH]``
    fp32 when the arena is int8, else None."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @classmethod
    def create(cls, config: llama.LlamaConfig, num_blocks: int,
               block_size: int, kv_dtype: str = "bf16") -> "PagedKVCache":
        kv_dtype = resolve_kv_dtype(kv_dtype)
        shape = (config.num_layers, num_blocks, block_size,
                 config.num_kv_heads, config.head_dim)
        if kv_dtype == "int8":
            return cls(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       k_scale=jnp.zeros(shape[:-1], jnp.float32),
                       v_scale=jnp.zeros(shape[:-1], jnp.float32))
        return cls(k=jnp.zeros(shape, config.dtype),
                   v=jnp.zeros(shape, config.dtype))

    def token_bytes(self) -> int:
        """Arena bytes one live token occupies across all layers (the
        live-traffic estimate the achieved-bandwidth gauges use)."""
        layers, _, _, kvh, d = self.k.shape
        n = 2 * layers * kvh * d * jnp.dtype(self.k.dtype).itemsize
        if self.k_scale is not None:
            n += 2 * layers * kvh * 4
        return n

    def _parts(self):
        parts = [("k", self.k), ("v", self.v)]
        if self.quantized:
            parts += [("k_scale", self.k_scale),
                      ("v_scale", self.v_scale)]
        return parts

    def gather_blocks(self, blocks: Sequence[int]):
        """Fetch the named arena blocks (K/V plus int8 scale sidecars)
        to host, packed into ONE contiguous uint8 staging buffer.
        Returns ``(staging, layout)`` where ``layout`` is
        ``[(name, dtype_str, shape, offset, nbytes), ...]`` — the
        per-array regions are zero-copy VIEWS of the staging buffer, so
        a transfer plane ships one buffer + a small manifest, never a
        pickle of the arena (see :func:`unpack_staging`)."""
        import numpy as np

        idx = jnp.asarray(list(blocks), dtype=jnp.int32)
        host = [(name, np.asarray(arr[:, idx]))
                for name, arr in self._parts()]
        staging = np.empty(sum(a.nbytes for _, a in host), np.uint8)
        layout = []
        off = 0
        for name, a in host:
            end = off + a.nbytes
            staging[off:end].view(a.dtype).reshape(a.shape)[...] = a
            layout.append((name, str(a.dtype), a.shape, off, a.nbytes))
            off = end
        return staging, layout

    def scatter_blocks(self, blocks: Sequence[int], staging,
                       layout) -> "PagedKVCache":
        """Land a :meth:`gather_blocks` staging buffer in THIS arena's
        ``blocks`` through the same ``.at[:, idx].set`` table-scatter
        path prefill write-back uses. Returns the new cache value."""
        views = unpack_staging(staging, layout)
        idx = jnp.asarray(list(blocks), dtype=jnp.int32)
        fields = {}
        for name, arr in self._parts():
            src = views[name]
            if src.shape[1] != len(blocks):
                raise ValueError(
                    f"scatter_blocks: payload carries {src.shape[1]} "
                    f"blocks for {name}, caller named {len(blocks)}")
            fields[name] = arr.at[:, idx].set(
                jnp.asarray(src, dtype=arr.dtype))
        return PagedKVCache(**fields)


class BlockAllocator:
    """Host-side free-list over arena block ids. Block 0 (GARBAGE_BLOCK)
    is never handed out: freed slots keep scattering their masked-lane
    garbage there. LIFO reuse keeps hot blocks hot."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("paged arena needs >= 2 blocks "
                             "(block 0 is reserved)")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set = set()   # O(1) double-free detection

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._allocated)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None (all-or-nothing) when the arena can't cover
        them — the caller leaves the request queued."""
        if n <= 0:
            return []      # [-0:] would slice (and drain) the whole list
        if n > len(self._free):
            return None
        taken = self._free[-n:][::-1]
        del self._free[-n:]
        self._allocated.update(taken)
        return taken

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise ValueError("cannot free the reserved garbage block")
            if b not in self._allocated:
                raise ValueError(f"double free / bad block id {b}")
        self._allocated.difference_update(blocks)
        self._free.extend(reversed(blocks))

    def reset(self) -> None:
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._allocated.clear()


class _RadixNode:
    """One block-aligned chunk in the prefix tree. ``refs`` counts the
    slots currently reading this block through their tables; 0 parks the
    node in the index LRU (block content stays valid in the arena)."""

    __slots__ = ("chunk", "block", "parent", "children", "refs")

    def __init__(self, chunk: Optional[Tuple[int, ...]], block: int,
                 parent: Optional["_RadixNode"]):
        self.chunk = chunk
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.refs = 0


class RadixBlockIndex:
    """Radix index over block-aligned token-id chunks → arena block ids.

    Keys are EXACT token tuples (dict equality, no lossy hashing — a
    hash collision would silently serve another prompt's K/V), chained
    parent→child so chunk ``i``'s node is reachable only through the
    full token prefix ``[0, (i+1)·bs)`` that determines its K/V content
    (causal attention: position ``p`` depends on tokens ``[0..p]``).

    Refcount/eviction rules (the engine's shared-block contract):

    * :meth:`match` pins every matched node (``refs += 1``; revived out
      of the LRU) — matched blocks are spliced into a slot's table
      READ-ONLY and must never be reclaimed or written while pinned;
    * :meth:`insert` indexes a slot's newly-prefilled full-prompt blocks
      (pinned, refs=1); a chunk already indexed under a different block
      — two cold twins racing one admission round — stops the walk and
      leaves the loser's remaining blocks exclusive (freed on release);
    * :meth:`release` unpins; refs==0 parks the node at the LRU's young
      end instead of freeing its block;
    * :meth:`evict` reclaims parked blocks LEAF-FIRST in LRU order, so
      a popular prefix's root chunks outlive its cold tails. Every
      slot pins a contiguous root-chain, so a parked node can never
      have a pinned descendant — leaf-first eviction never strands a
      live reader.
    """

    def __init__(self):
        self._root = _RadixNode(None, GARBAGE_BLOCK, None)
        self._lru: "OrderedDict[_RadixNode, None]" = OrderedDict()
        self._live = 0          # nodes with refs >= 1
        self._by_block: Dict[int, _RadixNode] = {}

    # ------------------------------------------------------------ stats
    @property
    def cached_count(self) -> int:
        """Parked refcount-0 blocks the arena can reclaim."""
        return len(self._lru)

    @property
    def shared_count(self) -> int:
        """Indexed blocks currently pinned by at least one slot."""
        return self._live

    @property
    def indexed_count(self) -> int:
        return len(self._by_block)

    # ------------------------------------------------------------- read
    def match_nodes(self,
                    chunks: Sequence[Tuple[int, ...]]) -> List[_RadixNode]:
        """Longest indexed prefix, read-only (NO pinning): the
        admission-feasibility probe inspects the nodes' refcounts — a
        parked (refs==0) matched block covers part of the request's
        need, but pinning it revives it from the LRU without freeing
        anything, so the probe must not also count it as evictable."""
        node, out = self._root, []
        for chunk in chunks:
            node = node.children.get(chunk)
            if node is None:
                break
            out.append(node)
        return out

    def match_len(self, chunks: Sequence[Tuple[int, ...]]) -> int:
        """Longest indexed prefix, in blocks — read-only (no pinning)."""
        return len(self.match_nodes(chunks))

    # ------------------------------------------------------------ write
    def match(self, chunks: Sequence[Tuple[int, ...]]) -> List[_RadixNode]:
        """Longest indexed prefix, PINNED: each matched node's refcount
        rises (reviving it from the LRU), so the caller may splice the
        blocks into a live table. Pair with :meth:`release`."""
        node, out = self._root, []
        for chunk in chunks:
            node = node.children.get(chunk)
            if node is None:
                break
            self._pin(node)
            out.append(node)
        return out

    def insert(self, chunks: Sequence[Tuple[int, ...]],
               blocks: Sequence[int], start: int = 0) -> List[_RadixNode]:
        """Index ``blocks[start:]`` under ``chunks[start:]`` (the chunks
        before ``start`` were matched — their nodes already exist and are
        pinned by this caller). Returns the nodes CREATED (pinned,
        refs=1). A chunk already indexed under a *different* block stops
        the walk: the caller's remaining blocks stay exclusive."""
        node = self._root
        for chunk in chunks[:start]:
            node = node.children[chunk]   # matched path must exist
        created: List[_RadixNode] = []
        for i in range(start, len(chunks)):
            child = node.children.get(chunks[i])
            if child is not None:
                if child.block != blocks[i]:
                    break                 # cold twin lost the race
                node = child
                continue
            child = _RadixNode(chunks[i], blocks[i], node)
            node.children[chunks[i]] = child
            self._by_block[blocks[i]] = child
            self._pin(child)
            created.append(child)
            node = child
        return created

    def release(self, nodes: Sequence[_RadixNode]) -> None:
        """Unpin (slot released its table): refcount 0 parks the node at
        the LRU young end — the block stays resident until reclaimed."""
        for node in nodes:
            node.refs -= 1
            assert node.refs >= 0, "prefix node over-released"
            if node.refs == 0:
                self._live -= 1
                self._lru[node] = None

    def evict(self, want: int) -> List[int]:
        """Reclaim up to ``want`` parked blocks, leaf-first in LRU order;
        returns their ids (the caller hands them back to the
        allocator's free list). Pinned nodes are untouchable — a parked
        node never has pinned descendants (contiguous root-chain pins),
        so every parked block is reachable leaf-first. A parent joins
        the candidate queue the moment its last child drops, keeping a
        deep parked chain O(evicted) instead of one full LRU rescan per
        tree level (this runs synchronously on the admission path)."""
        out: List[int] = []
        ready = deque(nd for nd in self._lru if not nd.children)
        while len(out) < want and ready:
            node = ready.popleft()
            if node.children or node not in self._lru:
                continue                  # defensive: invariant violated
            parent = node.parent
            self._drop(node)
            out.append(node.block)
            if (parent is not None and not parent.children
                    and parent in self._lru):
                ready.append(parent)
        return out

    def clear(self) -> None:
        self._root = _RadixNode(None, GARBAGE_BLOCK, None)
        self._lru.clear()
        self._by_block.clear()
        self._live = 0

    # ---------------------------------------------------------- helpers
    def _pin(self, node: _RadixNode) -> None:
        if node.refs == 0:
            self._live += 1
            self._lru.pop(node, None)
        node.refs += 1

    def _drop(self, node: _RadixNode) -> None:
        self._lru.pop(node, None)
        self._by_block.pop(node.block, None)
        if node.parent is not None:
            node.parent.children.pop(node.chunk, None)


def unpack_staging(staging, layout):
    """Reconstruct the per-array views of a gather_blocks staging
    buffer: ``{name: ndarray}``, each a zero-copy view into
    ``staging``. The buffer may have crossed a process boundary (shm
    channel read) — only the bytes moved, never a per-array pickle."""
    import numpy as np

    buf = np.frombuffer(memoryview(staging), np.uint8) \
        if not isinstance(staging, np.ndarray) else staging
    out = {}
    for name, dtype, shape, off, nbytes in layout:
        out[name] = buf[off:off + nbytes].view(np.dtype(dtype)) \
            .reshape(shape)
    return out


def prompt_chunks(prompt_tokens: Sequence[int],
                  block_size: int) -> List[Tuple[int, ...]]:
    """Block-aligned chunk keys for the SHAREABLE region of a prompt:
    only blocks filled entirely by prompt tokens are deterministic
    across requests (the tail block mixes prompt and generated tokens),
    and a matcher must leave ≥1 prompt token to prefill — the first
    token is sampled from the last prompt position's logits, which the
    KV cache does not store — so matching is additionally capped at
    ``(len(prompt) - 1) // block_size`` by the engine."""
    n = len(prompt_tokens) // block_size
    return [tuple(prompt_tokens[i * block_size:(i + 1) * block_size])
            for i in range(n)]
