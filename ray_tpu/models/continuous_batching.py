"""Continuous batching: iteration-level scheduling for LLM serving.

Reference: the vLLM-style engine behind ``ray.serve.llm``
(``python/ray/llm/_internal/serve``) — instead of batching whole
requests (head-of-line blocking on the longest generation), the engine
owns a fixed pool of KV-cache slots; requests prefill into a free slot
and join the very next decode tick, and finished requests free their
slot immediately for queued work.

TPU-native shape discipline: the decode tick is ONE jitted program over
all ``num_slots`` slots (static shapes; inactive slots compute masked
garbage), per-slot absolute positions drive RoPE/cache scatter/causal
masking, and prompt prefills pad to power-of-two buckets so the number
of compiled programs stays logarithmic. Padded prefill is sound without
length masking because a slot's garbage cache entries live only at
positions strictly greater than its next decode position — every decode
overwrites position ``p`` before attending ``[0..p]``.

The KV data plane is PAGED by default (``models/paged_kv.py``): slots
share one block arena through per-slot block tables, so a tick's
attention streams only the blocks a slot actually filled — no
``S_max`` padding traffic — with optional int8 arena storage halving
bytes-per-token again. ``paged=False`` keeps the dense pooled cache
(one private ``[S_max]`` stripe per slot). Sampling (temperature/top-p)
runs in-device inside the tick jit either way; only token ids cross to
the host.

CROSS-REQUEST PREFIX CACHING (default on for paged engines,
``prefix_cache`` / ``RAY_TPU_PREFIX_CACHE``): admission matches each
prompt's longest block-aligned prefix against a radix index of blocks
already resident in the arena (``paged_kv.RadixBlockIndex``), splices
the matched blocks into the slot's table READ-ONLY (decode writes start
at the prompt tail, and speculative overruns redirect to the garbage
block — a shared block is never a write target), and prefills ONLY the
suffix — prefill compute and HBM traffic scale with *novel* tokens, not
total tokens. Released prompt blocks park in an LRU "cached" state that
arena pressure reclaims before admission ever blocks. Greedy outputs
are bit-identical with the prefix cache on or off (bf16 and int8
arenas, paged kernel on or off): int8 prefill quantizes K/V IN-LOOP and
attends the dequantized values, so a later prefix-sharer reading the
arena back attends exactly what the original prefill attended.
"""

from __future__ import annotations

import itertools
import os
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private import xla_monitor
from ray_tpu.models import llama
from ray_tpu.models.inference import (ExternalLlamaDrafter, KVCache,
                                      SelfDrafter, _attend_cached,
                                      _forward_cached, lm_head_logits)
from ray_tpu.models.llama import rms_norm
from ray_tpu.models.paged_kv import (GARBAGE_BLOCK, BlockAllocator,
                                     PagedKVCache, RadixBlockIndex,
                                     prompt_chunks, quantize_kv,
                                     resolve_kv_dtype)
from ray_tpu.models.sampling import (SPEC_DRAFT_SALT, SamplingParams,
                                     filtered_probs, sample_tokens,
                                     spec_commit, step_key)
from ray_tpu.ops.decode_attention import (decode_applicable,
                                          decode_attention,
                                          decode_attention_reference,
                                          env_flag)
from ray_tpu.ops.paged_decode_attention import (paged_applicable,
                                                paged_decode_attention)
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.util import tracing


def _apply_rope_batched(x, cos, sin):
    """RoPE with per-batch angles: x [B, 1, H, D], cos/sin [B, D//2]."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, None, None, :]
    s = sin[:, None, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(dtype)


def _scatter_slot(cache, new, positions):
    """cache [B, S_max, KVH, D]; new [B, KVH, D] written at per-slot
    ``positions`` [B]."""
    def one(c, n, p):
        return jax.lax.dynamic_update_slice(c, n[None], (p, 0, 0))

    return jax.vmap(one)(cache, new, positions)


def _scatter_arena(arena, new, flat_pos):
    """Paged scatter: arena [NB, bs, ...] viewed flat over tokens; one
    entry per slot written at ``flat_pos`` [B] (= block_id * bs +
    offset). Freed slots all target the garbage block — duplicate
    indices write byte-garbage there, which nothing ever attends."""
    nb, bs = arena.shape[0], arena.shape[1]
    flat = arena.reshape(nb * bs, *arena.shape[2:])
    flat = flat.at[flat_pos].set(new.astype(arena.dtype))
    return flat.reshape(arena.shape)


# The XLA reference single-query attention lives next to the fused
# kernel (ops/decode_attention.py); keep the old name importable — it is
# the parity baseline the kernel tests compare against.
_attend_decode = decode_attention_reference


def _next_tokens(logits, step, sampling: SamplingParams, salt: int = 0):
    """In-device token selection from tick/prefill logits [B, 1, V]:
    greedy argmax, or temperature/top-p sampling keyed off the
    device-threaded ``step`` counter (deterministic under a fixed seed,
    including speculative-rewind replays of the same step). ``salt``
    separates the prefill and decode key streams — their counters both
    start at 0, and an unsalted collision would correlate prefill
    first-token draws with the first decode tick's."""
    row = logits[:, 0]
    if sampling.greedy:
        return jnp.argmax(row, axis=-1).astype(jnp.int32)
    key = step_key(sampling.seed, step, salt=salt)
    return sample_tokens(row, key, sampling.temperature, sampling.top_p)


_PREFILL_SALT = 1  # prefill sampling stream, distinct from decode's


def _layer_qkv(x, layer, cos, sin, c):
    """Shared per-layer projections for the dense and paged ticks:
    attn-norm, Q/K/V einsums, RoPE on Q and K (V unrotated). Any
    numerics change here reaches both data planes at once — the
    paged-on/off bit-parity contract depends on that."""
    h = rms_norm(x, layer["attn_norm"], c.rms_eps)
    q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(c.dtype))
    k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(c.dtype))
    v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(c.dtype))
    return (_apply_rope_batched(q, cos, sin),
            _apply_rope_batched(k, cos, sin), v)


def _layer_finish(x, o, layer, c):
    """Shared per-layer tail: attention output projection + gated MLP."""
    x = x + jnp.einsum("bhd,hde->be", o,
                       layer["wo"].astype(c.dtype))[:, None, :]
    h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(c.dtype))
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(c.dtype))
    return x + jnp.einsum("bsm,me->bse", jax.nn.silu(gate) * up,
                          layer["w_down"].astype(c.dtype))


def _apply_rope_window(x, cos, sin):
    """RoPE with per-(slot, position) angles: x [B, S, H, D], cos/sin
    [B, S, D//2] — the k+1-token verify-window analog of
    :func:`_apply_rope_batched` (same elementwise math, broadcast over
    heads instead of over a singleton window)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(dtype)


def _layer_qkv_window(x, layer, cos, sin, c):
    """:func:`_layer_qkv` over a k+1-token verify window: x [B, S, E],
    per-(slot, position) RoPE angles [B, S, D//2]. The projections are
    the same contractions as the s=1 tick — the window rides the batch
    dims, the E-axis accumulation is untouched — which the spec-on/off
    bit-parity tests pin down."""
    h = rms_norm(x, layer["attn_norm"], c.rms_eps)
    q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(c.dtype))
    k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(c.dtype))
    v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(c.dtype))
    return (_apply_rope_window(q, cos, sin),
            _apply_rope_window(k, cos, sin), v)


def _layer_finish_window(x, o, layer, c):
    """:func:`_layer_finish` over a verify window: o [B, S, H, D]."""
    x = x + jnp.einsum("bshd,hde->bse", o, layer["wo"].astype(c.dtype))
    h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(c.dtype))
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(c.dtype))
    return x + jnp.einsum("bsm,me->bse", jax.nn.silu(gate) * up,
                          layer["w_down"].astype(c.dtype))


def _draft_forward_paged(params, n_draft, tokens, positions, tables,
                         limits, cache: PagedKVCache,
                         config: llama.LlamaConfig, use_kernel: bool):
    """One self-draft forward: tokens [B] at ``positions`` through the
    FIRST ``n_draft`` target layers, reading and writing the target's
    OWN paged arena (same tables/limits/garbage redirect as the tick).
    The truncated stack computes bitwise the target's layer-[0:n) K/V,
    so context is already resident and the draft's writes are the bytes
    verify will rewrite identically. Returns (draft logits [B, V]
    through the target's final norm + lm_head, updated cache)."""
    c = config
    quantized = cache.quantized
    bs = cache.block_size
    cos, sin = rope_frequencies(c.head_dim, 0, c.rope_theta,
                                positions=positions)
    x = params["embed"].astype(c.dtype)[tokens][:, None, :]
    scale = c.head_dim ** -0.5
    gathered = jnp.take_along_axis(
        tables, (positions // bs)[:, None], axis=1)[:, 0]
    block_idx = jnp.where(positions < limits, gathered, GARBAGE_BLOCK)
    flat_pos = block_idx * bs + positions % bs

    def layer_fn(carry, layer):
        x, ck_all, cv_all, ks_all, vs_all, li = carry
        ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
        q, k, v = _layer_qkv(x, layer, cos, sin, c)
        k_tok, v_tok = k[:, 0], v[:, 0]
        ksl = vsl = None
        if quantized:
            kq, ksc = quantize_kv(k_tok)
            vq, vsc = quantize_kv(v_tok)
            ksl = jax.lax.dynamic_index_in_dim(ks_all, li, 0,
                                               keepdims=False)
            vsl = jax.lax.dynamic_index_in_dim(vs_all, li, 0,
                                               keepdims=False)
            ksl = _scatter_arena(ksl, ksc, flat_pos)
            vsl = _scatter_arena(vsl, vsc, flat_pos)
        else:
            kq, vq = k_tok, v_tok
        ck = _scatter_arena(ck, kq, flat_pos)
        cv = _scatter_arena(cv, vq, flat_pos)
        o = paged_decode_attention(q[:, 0], ck, cv, tables, positions,
                                   scale, k_scale=ksl, v_scale=vsl,
                                   use_kernel=use_kernel)
        o = o.astype(x.dtype)
        ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
        cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
        if quantized:
            ks_all = jax.lax.dynamic_update_index_in_dim(ks_all, ksl,
                                                         li, 0)
            vs_all = jax.lax.dynamic_update_index_in_dim(vs_all, vsl,
                                                         li, 0)
        x = _layer_finish(x, o, layer, c)
        return (x, ck_all, cv_all, ks_all, vs_all, li + 1), None

    sliced = jax.tree.map(lambda a: a[:n_draft], params["layers"])
    carry0 = (x, cache.k, cache.v, cache.k_scale, cache.v_scale,
              jnp.int32(0))
    (x, nk, nv, nks, nvs, _), _ = jax.lax.scan(layer_fn, carry0, sliced)
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    logits = lm_head_logits(x, params, c)
    return logits[:, 0], PagedKVCache(k=nk, v=nv, k_scale=nks,
                                      v_scale=nvs)


def _draft_forward_dense(dparams, tokens, positions, dcache: KVCache,
                         dconfig: llama.LlamaConfig):
    """External-drafter decode step over the drafter's own dense
    per-slot cache (reference attention — the drafter is small by
    construction, so the fused kernel buys nothing). Returns
    (logits [B, V], updated cache)."""
    c = dconfig
    cos, sin = rope_frequencies(c.head_dim, 0, c.rope_theta,
                                positions=positions)
    x = dparams["embed"].astype(c.dtype)[tokens][:, None, :]
    scale = c.head_dim ** -0.5

    def layer_fn(carry, layer):
        x, ck_all, cv_all, li = carry
        ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
        q, k, v = _layer_qkv(x, layer, cos, sin, c)
        ck = _scatter_slot(ck, k[:, 0].astype(ck.dtype), positions)
        cv = _scatter_slot(cv, v[:, 0].astype(cv.dtype), positions)
        o = decode_attention(q[:, 0], ck, cv, positions, scale,
                             use_kernel=False)
        ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
        cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
        x = _layer_finish(x, o, layer, c)
        return (x, ck_all, cv_all, li + 1), None

    (x, nk, nv, _), _ = jax.lax.scan(
        layer_fn, (x, dcache.k, dcache.v, jnp.int32(0)),
        dparams["layers"])
    x = rms_norm(x, dparams["final_norm"], c.rms_eps)
    logits = lm_head_logits(x, dparams, c)
    return logits[:, 0], KVCache(k=nk, v=nv)


def _verify_forward_paged(params, tokens, positions, tables, limits,
                          cache: PagedKVCache, config: llama.LlamaConfig,
                          use_kernel: bool):
    """ONE batched verify pass over each slot's k+1-token window: tokens
    [B, S] at per-slot absolute ``positions`` [B, S] (= p .. p+k).

    Projections and the MLP run batched over the window — verify streams
    the parameters ONCE for all k+1 positions, which is the speculative
    roofline lever — while attention runs per window position through the
    EXISTING paged decode path. All k+1 positions' K/V scatter before any
    query attends, which is safe because position masking hides in-window
    successors (query j sees [0..p+j] only), and overrun/freed-slot
    writes redirect to the garbage block exactly like the plain tick.
    Returns (fp32 logits [B, S, V], updated cache)."""
    c = config
    quantized = cache.quantized
    bs = cache.block_size
    b, s = tokens.shape
    cos, sin = rope_frequencies(c.head_dim, 0, c.rope_theta,
                                positions=positions.reshape(-1))
    cos = cos.reshape(b, s, -1)
    sin = sin.reshape(b, s, -1)
    x = params["embed"].astype(c.dtype)[tokens]               # [B, S, E]
    scale = c.head_dim ** -0.5
    gathered = jnp.take_along_axis(tables, positions // bs, axis=1)
    block_idx = jnp.where(positions < limits[:, None], gathered,
                          GARBAGE_BLOCK)
    flat_pos = (block_idx * bs + positions % bs).reshape(-1)  # [B*S]

    def layer_fn(carry, layer):
        x, ck_all, cv_all, ks_all, vs_all, li = carry
        ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
        q, k, v = _layer_qkv_window(x, layer, cos, sin, c)
        k_tok = k.reshape(b * s, *k.shape[2:])
        v_tok = v.reshape(b * s, *v.shape[2:])
        ksl = vsl = None
        if quantized:
            # Per-token/per-head scales reduce over D only, so the
            # window-batched quantize is bitwise the tick's.
            kq, ksc = quantize_kv(k_tok)
            vq, vsc = quantize_kv(v_tok)
            ksl = jax.lax.dynamic_index_in_dim(ks_all, li, 0,
                                               keepdims=False)
            vsl = jax.lax.dynamic_index_in_dim(vs_all, li, 0,
                                               keepdims=False)
            ksl = _scatter_arena(ksl, ksc, flat_pos)
            vsl = _scatter_arena(vsl, vsc, flat_pos)
        else:
            kq, vq = k_tok, v_tok
        ck = _scatter_arena(ck, kq, flat_pos)
        cv = _scatter_arena(cv, vq, flat_pos)
        outs = []
        for j in range(s):  # unrolled: s = k+1, small and static
            outs.append(paged_decode_attention(
                q[:, j], ck, cv, tables, positions[:, j], scale,
                k_scale=ksl, v_scale=vsl, use_kernel=use_kernel))
        o = jnp.stack(outs, axis=1).astype(x.dtype)       # [B, S, H, D]
        ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
        cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
        if quantized:
            ks_all = jax.lax.dynamic_update_index_in_dim(ks_all, ksl,
                                                         li, 0)
            vs_all = jax.lax.dynamic_update_index_in_dim(vs_all, vsl,
                                                         li, 0)
        x = _layer_finish_window(x, o, layer, c)
        return (x, ck_all, cv_all, ks_all, vs_all, li + 1), None

    carry0 = (x, cache.k, cache.v, cache.k_scale, cache.v_scale,
              jnp.int32(0))
    (x, nk, nv, nks, nvs, _), _ = jax.lax.scan(layer_fn, carry0,
                                               params["layers"])
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    logits = lm_head_logits(x, params, c)
    return logits, PagedKVCache(k=nk, v=nv, k_scale=nks, v_scale=nvs)


def _spec_tick_paged(params, tokens, positions, tables, limits,
                     cache: PagedKVCache, step,
                     config: llama.LlamaConfig, k: int, n_draft: int,
                     use_kernel: bool, sampling: SamplingParams,
                     draft_params=None, draft_cache=None,
                     draft_config=None):
    """Speculative decode tick: draft ``k`` tokens per slot, score all
    k+1 window positions in ONE batched verify pass, accept per slot
    in-device (:func:`~ray_tpu.models.sampling.spec_commit`).

    Returns ``(committed [B, k+1], counts [B], next_tokens [B],
    next_positions [B], cache, draft_cache, step + 1)`` — the device
    threads its own next-token/next-position state exactly like the
    plain tick, so buffered mode runs spec ticks back-to-back without a
    host sync. Rejected draft writes land past each slot's committed
    length inside its (k-lookahead-extended) reservation and are dead on
    arrival: every future decode overwrites a position before attending
    it, and a buffered rewind simply re-uploads host counts — the
    garbage-block redirect + replay machinery, unchanged."""
    external = draft_params is not None
    d_tokens: List[Any] = []
    d_probs: List[Any] = []
    tok = tokens
    pos = positions
    dcache = draft_cache if external else cache
    draft_key = None if sampling.greedy else step_key(
        sampling.seed, step, salt=SPEC_DRAFT_SALT)
    for i in range(k):
        if external:
            logits_d, dcache = _draft_forward_dense(
                draft_params, tok, pos, dcache, draft_config)
        else:
            logits_d, dcache = _draft_forward_paged(
                params, n_draft, tok, pos, tables, limits, dcache,
                config, use_kernel)
        if sampling.greedy:
            nxt = jnp.argmax(logits_d, axis=-1).astype(jnp.int32)
        else:
            # The drafter proposes from its OWN filtered distribution;
            # acceptance needs those q rows, and the proposal stream is
            # salted apart from accept/fix/base-tick draws.
            d_probs.append(filtered_probs(
                logits_d, sampling.temperature, sampling.top_p))
            nxt = sample_tokens(logits_d, jax.random.fold_in(draft_key, i),
                                sampling.temperature, sampling.top_p)
        d_tokens.append(nxt)
        tok = nxt
        pos = pos + 1
    if not external:
        cache = dcache  # self-draft wrote the shared arena layers [0:n)
    window = jnp.stack([tokens] + d_tokens, axis=1)          # [B, k+1]
    window_pos = positions[:, None] + jnp.arange(k + 1)[None, :]
    logits, cache = _verify_forward_paged(params, window, window_pos,
                                          tables, limits, cache, config,
                                          use_kernel)
    drafts = jnp.stack(d_tokens, axis=1)
    probs = jnp.stack(d_probs, axis=1) if d_probs else None
    committed, counts = spec_commit(drafts, probs, logits, step, sampling)
    next_tokens = jnp.take_along_axis(
        committed, (counts - 1)[:, None], axis=1)[:, 0]
    next_positions = positions + counts
    return (committed, counts, next_tokens, next_positions, cache,
            dcache if external else None, step + 1)


def _decode_tick(params, tokens, positions, cache: KVCache, step,
                 config: llama.LlamaConfig, use_kernel: bool = False,
                 sampling: SamplingParams = SamplingParams()):
    """One decode step for every slot: tokens [B] at per-slot absolute
    ``positions`` [B]. Returns (next_tokens [B], positions+1, cache,
    step+1) — ``step`` is the device-resident sampling counter.

    ``use_kernel`` (static) routes attention through the fused pallas
    decode kernel — one pass over the KV pool in its storage dtype —
    instead of the fp32-upcast whole-cache einsums of the reference."""
    c = config
    cos, sin = rope_frequencies(c.head_dim, 0, c.rope_theta,
                                positions=positions)  # [B, D//2]
    x = params["embed"].astype(c.dtype)[tokens][:, None, :]   # [B, 1, E]
    scale = c.head_dim ** -0.5

    def layer_fn(carry, inputs):
        # Cache rides the CARRY (updated in place layer by layer via
        # dynamic_update_slice), not scan xs/ys: threading it as
        # per-iteration inputs/outputs made XLA materialize full cache
        # copies every tick — the decode tick was 2-3x the HBM roofline
        # from copy traffic alone.
        x, ck_all, cv_all, li = carry
        layer = inputs
        ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
        q, k, v = _layer_qkv(x, layer, cos, sin, c)
        ck = _scatter_slot(ck, k[:, 0].astype(ck.dtype), positions)
        cv = _scatter_slot(cv, v[:, 0].astype(cv.dtype), positions)
        o = decode_attention(q[:, 0], ck, cv, positions, scale,
                             use_kernel=use_kernel)
        ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
        cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
        x = _layer_finish(x, o, layer, c)
        return (x, ck_all, cv_all, li + 1), None

    (x, new_k, new_v, _), _ = jax.lax.scan(
        layer_fn, (x, cache.k, cache.v, jnp.int32(0)), params["layers"])
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    # lm_head in the params' storage dtype with fp32 accumulation (shared
    # with the prefill path) — bf16 params are no longer upcast in HBM.
    logits = lm_head_logits(x, params, c)
    # Token selection stays ON DEVICE: the host needs 4 bytes per slot,
    # not the [B, V] logits — shipping full logits per tick was the
    # serving bottleneck on remote-attached chips (512KB x RTT per token).
    next_tokens = _next_tokens(logits, step, sampling)
    return next_tokens, positions + 1, KVCache(k=new_k, v=new_v), step + 1


def _decode_tick_paged(params, tokens, positions, tables, limits,
                       cache: PagedKVCache, step,
                       config: llama.LlamaConfig, use_kernel: bool = False,
                       sampling: SamplingParams = SamplingParams()):
    """Paged decode step: same per-layer structure as :func:`_decode_tick`
    but K/V scatter/attention go through the block arena + per-slot
    block tables, so the attention streams only live blocks. ``tables``
    [B, max_blocks] int32 (dead tail entries repeat the last live block;
    freed slots point wholesale at the garbage block); ``limits`` [B] is
    each slot's table-covered token count (reserved_blocks * bs)."""
    c = config
    quantized = cache.quantized
    bs = cache.block_size
    cos, sin = rope_frequencies(c.head_dim, 0, c.rope_theta,
                                positions=positions)
    x = params["embed"].astype(c.dtype)[tokens][:, None, :]
    scale = c.head_dim ** -0.5
    # This tick writes at `positions`: resolve each slot's target block
    # through its table once (shared by every layer's scatter).
    # Speculative ticks can OVERRUN a slot's reservation (the host
    # detects finishes up to 2K ticks late): past ``limits`` the table
    # tail would alias the write onto the slot's LAST LIVE block — and a
    # later rewind would replay over the corrupted K/V. Redirect overrun
    # writes to the garbage block instead (the dense engine's analog:
    # overrun writes land in the slot's private tail, harmlessly).
    gathered = jnp.take_along_axis(
        tables, (positions // bs)[:, None], axis=1)[:, 0]        # [B]
    block_idx = jnp.where(positions < limits, gathered, GARBAGE_BLOCK)
    flat_pos = block_idx * bs + positions % bs                   # [B]

    def layer_fn(carry, layer):
        x, ck_all, cv_all, ks_all, vs_all, li = carry
        ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
        q, k, v = _layer_qkv(x, layer, cos, sin, c)
        k_tok, v_tok = k[:, 0], v[:, 0]                  # [B, KVH, D]
        ksl = vsl = None
        if quantized:
            kq, ksc = quantize_kv(k_tok)
            vq, vsc = quantize_kv(v_tok)
            ksl = jax.lax.dynamic_index_in_dim(ks_all, li, 0,
                                               keepdims=False)
            vsl = jax.lax.dynamic_index_in_dim(vs_all, li, 0,
                                               keepdims=False)
            ksl = _scatter_arena(ksl, ksc, flat_pos)
            vsl = _scatter_arena(vsl, vsc, flat_pos)
        else:
            kq, vq = k_tok, v_tok
        ck = _scatter_arena(ck, kq, flat_pos)
        cv = _scatter_arena(cv, vq, flat_pos)
        o = paged_decode_attention(q[:, 0], ck, cv, tables, positions,
                                   scale, k_scale=ksl, v_scale=vsl,
                                   use_kernel=use_kernel)
        o = o.astype(x.dtype)
        ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
        cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
        if quantized:
            ks_all = jax.lax.dynamic_update_index_in_dim(ks_all, ksl,
                                                         li, 0)
            vs_all = jax.lax.dynamic_update_index_in_dim(vs_all, vsl,
                                                         li, 0)
        x = _layer_finish(x, o, layer, c)
        return (x, ck_all, cv_all, ks_all, vs_all, li + 1), None

    carry0 = (x, cache.k, cache.v, cache.k_scale, cache.v_scale,
              jnp.int32(0))
    (x, nk, nv, nks, nvs, _), _ = jax.lax.scan(layer_fn, carry0,
                                               params["layers"])
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    logits = lm_head_logits(x, params, c)
    next_tokens = _next_tokens(logits, step, sampling)
    new_cache = PagedKVCache(k=nk, v=nv, k_scale=nks, v_scale=nvs)
    return next_tokens, positions + 1, new_cache, step + 1


def _prefill_forward_paged(params, tokens, positions, pk, pv, config,
                           quantized):
    """Prefill forward over ``[shared prefix ++ suffix]``.

    ``tokens`` [N, S] are the suffix at absolute ``positions`` [S]
    (= P + arange(S), shared by the group — admission groups rows by
    matched-prefix length); ``pk``/``pv`` [L, N, P, KVH, D] hold the
    prefix K/V exactly as attention must read them (the dequantized
    arena storage). Returns ``(logits [N, S, V], stored)`` where
    ``stored`` is the suffix K/V in ARENA form — int8 arenas quantize
    IN-LOOP and attention reads the dequantized values, so what a later
    prefix-sharer gathers back from the arena is bit-identical to what
    this prefill attended: the prefix-cache on/off parity contract.
    With P=0 and no quantization this computes exactly what the dense
    mini-cache prefill (:func:`~ray_tpu.models.inference._forward_cached`)
    computed — same ops in the same order — so paged-vs-dense parity is
    untouched."""
    c = config
    cos, sin = rope_frequencies(c.head_dim, tokens.shape[1], c.rope_theta,
                                positions=positions)
    x = params["embed"].astype(c.dtype)[tokens]
    scale = c.head_dim ** -0.5

    def layer_fn(x, inputs):
        layer, pk_l, pv_l = inputs
        h = rms_norm(x, layer["attn_norm"], c.rms_eps)
        q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(c.dtype))
        k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(c.dtype))
        v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(c.dtype))
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if quantized:
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            k_att = (kq.astype(jnp.float32)
                     * ksc[..., None]).astype(c.dtype)
            v_att = (vq.astype(jnp.float32)
                     * vsc[..., None]).astype(c.dtype)
            stored = (kq, vq, ksc, vsc)
        else:
            k_att, v_att = k, v
            stored = (k, v)
        ck = jnp.concatenate([pk_l, k_att], axis=1)   # [N, P+S, KVH, D]
        cv = jnp.concatenate([pv_l, v_att], axis=1)
        o = _attend_cached(q, ck, cv, positions, scale)
        x = x + jnp.einsum("bshd,hde->bse", o, layer["wo"].astype(c.dtype))
        h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
        gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(c.dtype))
        up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(c.dtype))
        x = x + jnp.einsum("bsm,me->bse", jax.nn.silu(gate) * up,
                           layer["w_down"].astype(c.dtype))
        return x, stored

    x, stored = jax.lax.scan(layer_fn, x, (params["layers"], pk, pv))
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    logits = lm_head_logits(x, params, c)
    return logits, stored


def _bucket(n: int, floor: int = 16) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _bucket_floor(n: int) -> int:
    """Largest power of two <= n (0 for 0). Matched-prefix block counts
    bucket DOWN through this: compiled prefill programs specialize on
    the prefix-table width m, so exact match lengths would compile one
    program per distinct length seen — a retrace storm under mixed
    system-prompt traffic. Bucketing keeps the program count
    log-bounded; the discarded match tail simply re-prefills with the
    suffix (bit-identical either way, just redundant compute)."""
    return 0 if n <= 0 else 1 << (n.bit_length() - 1)


def _resolve_paged(paged: Optional[bool]) -> bool:
    """Engine-level paging toggle: explicit arg > RAY_TPU_PAGED_KV env >
    on (the paged arena is the default data plane)."""
    if paged is None:
        paged = env_flag("RAY_TPU_PAGED_KV")
    if paged is None:
        return True
    return bool(paged)


def _resolve_prefix_cache(prefix_cache: Optional[bool]) -> bool:
    """Cross-request prefix reuse toggle: explicit arg >
    RAY_TPU_PREFIX_CACHE env > on. Only meaningful on paged engines —
    the radix index shares arena blocks, which the dense per-slot
    stripes cannot."""
    if prefix_cache is None:
        prefix_cache = env_flag("RAY_TPU_PREFIX_CACHE")
    if prefix_cache is None:
        return True
    return bool(prefix_cache)


# Versioned wire format of an exported KV handoff payload. Bump when the
# staging layout / manifest fields change: import refuses mismatched
# versions instead of scattering misinterpreted bytes into the arena.
HANDOFF_MANIFEST_VERSION = 1

_ROLES = ("prefill", "decode", "both")


def _resolve_role(role: Optional[str]) -> str:
    """Disaggregation role: explicit arg > RAY_TPU_SERVE_ROLE env >
    "both" (the colocated engine). "prefill" engines run admission +
    paged prefill only and park each request's finished arena blocks
    for export at its first token; "decode" engines additionally accept
    imported KV payloads but otherwise behave like "both"."""
    if role is None:
        role = os.environ.get("RAY_TPU_SERVE_ROLE", "").strip() or "both"
    role = str(role).lower()
    if role not in _ROLES:
        raise ValueError(
            f"role must be one of {_ROLES}, got {role!r}")
    return role


def _resolve_decode_kernel(config: llama.LlamaConfig, max_len: int,
                           use_decode_kernel: Optional[bool],
                           paged: bool = False,
                           block_size: int = 64) -> bool:
    """Engine-level kernel toggle: explicit arg > RAY_TPU_DECODE_KERNEL
    env > auto (fused kernel on TPU when the shapes tile; the XLA
    reference elsewhere — CPU tests opt in explicitly and run the kernel
    in interpret mode). The paged engine dispatches the paged kernel
    (``ops/paged_decode_attention.py``), the dense engine the dense
    one."""
    from ray_tpu.ops.decode_attention import pltpu as _pltpu

    if _pltpu is None:
        # No pallas TPU support in this jax build: the dispatcher would
        # silently run the reference, so report the truth.
        return False
    if use_decode_kernel is None:
        use_decode_kernel = env_flag("RAY_TPU_DECODE_KERNEL")
    if use_decode_kernel is None:
        if jax.default_backend() != "tpu":
            return False
        if paged:
            return paged_applicable(block_size, config.head_dim,
                                    config.num_heads, config.num_kv_heads)
        return decode_applicable(max_len, config.head_dim,
                                 config.num_heads, config.num_kv_heads)
    return bool(use_decode_kernel)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else default


def _resolve_spec_k(spec_k: Optional[int]) -> int:
    """Speculative depth: explicit arg > RAY_TPU_SPEC_K env > 0 (off)."""
    if spec_k is None:
        spec_k = _env_int("RAY_TPU_SPEC_K", 0)
    spec_k = int(spec_k)
    if spec_k < 0:
        raise ValueError(f"spec_k must be >= 0, got {spec_k}")
    return spec_k


def _resolve_spec_draft_layers(arg: Optional[int], num_layers: int) -> int:
    """Self-draft depth: explicit arg > RAY_TPU_SPEC_DRAFT_LAYERS env >
    num_layers // 4 (floor 1 — the EAGLE-style 'shallow slice of the
    target' default)."""
    if arg is None:
        arg = _env_int("RAY_TPU_SPEC_DRAFT_LAYERS",
                       max(1, num_layers // 4))
    arg = int(arg)
    if not 1 <= arg <= num_layers:
        raise ValueError(
            f"spec_draft_layers must be in [1, {num_layers}], got {arg}")
    return arg


def _spec_ladder(spec_k: int) -> List[int]:
    """Adaptive-k steps: powers of two up to spec_k, plus spec_k itself —
    log-bounded, so the compiled spec-tick program count is log-bounded
    too (one program per ladder rung, window dims whitelisted
    prefill_dims-style)."""
    ks = set()
    v = 1
    while v < spec_k:
        ks.add(v)
        v *= 2
    ks.add(spec_k)
    return sorted(ks)


class ContinuousBatcher:
    """Iteration-level scheduler over a fixed pool of KV-cache slots."""

    _engine_ids = itertools.count()  # per-process engine tag suffix

    def __init__(self, config: llama.LlamaConfig, params=None,
                 num_slots: int = 8, max_len: int = 512, seed: int = 0,
                 eos_token: Optional[int] = None, token_callback=None,
                 sync_every: int = 1,
                 use_decode_kernel: Optional[bool] = None,
                 paged: Optional[bool] = None,
                 block_size: int = 64,
                 kv_dtype: Optional[str] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 sampling=None,
                 spec_k: Optional[int] = None,
                 spec_draft_layers: Optional[int] = None,
                 spec_adaptive: Optional[bool] = None,
                 drafter=None,
                 role: Optional[str] = None):
        """``token_callback(rid, token)`` fires for every generated token
        as it is produced (serving streams ride this).

        ``sync_every=K > 1`` enables SPECULATIVE BUFFERED decode for
        high-latency host↔device links (remote-attached chips: a fetch
        costs a full tunnel RTT regardless of size): the engine runs K
        ticks per host synchronization, fetching token batches
        double-buffered so the transfer overlaps the next K ticks'
        compute. Decode is deterministic (greedy, and sampled decode is
        keyed off a device-threaded step counter), so ticks run ahead of
        host bookkeeping speculatively; when a request finishes, the
        engine rewinds to host-known state and redoes ≤2K ticks (freed
        slots need re-admission). Greedy outputs are bit-identical to
        ``sync_every=1``; only finish *detection* lags. Sampled outputs
        are bit-identical for a fixed submission schedule relative to
        buffer boundaries (e.g. everything submitted up front): a
        MID-RUN submission can admit at a different global tick than it
        would under ``sync_every=1``, and sampling keys are derived from
        that global step counter.

        ``use_decode_kernel`` routes decode attention through the fused
        pallas kernel (paged or dense variant); ``None`` resolves via
        ``RAY_TPU_DECODE_KERNEL`` then auto (TPU with tiling shapes).
        Outputs are bit-identical kernel on/off.

        PAGED KV plane (``paged``, default on; ``RAY_TPU_PAGED_KV=0``
        reverts the default): the cache is a shared arena of
        ``block_size``-token blocks with per-slot block tables — decode
        reads only live blocks instead of every slot's padded ``S_max``
        stripe, and admission reserves blocks all-or-nothing so a
        request can also wait on arena space. ``kv_dtype`` ('bf16' |
        'int8', or ``RAY_TPU_KV_DTYPE``) selects arena storage; int8
        halves KV bytes with per-token/per-head scales. ``num_blocks``
        sizes the arena (default: enough for every slot at ``max_len``,
        plus the reserved garbage block).

        ``prefix_cache`` (default on for paged engines;
        ``RAY_TPU_PREFIX_CACHE`` env) enables CROSS-REQUEST PREFIX
        REUSE: a radix index over block-aligned prompt chunks lets a
        new request splice blocks another request already prefilled
        into its table read-only and prefill only its novel suffix;
        released prompt blocks park in an LRU "cached" state reclaimed
        under arena pressure. Greedy outputs are bit-identical with the
        cache on or off.

        ``sampling`` (:class:`~ray_tpu.models.sampling.SamplingParams`
        or a dict) selects in-device token sampling; the default is
        greedy argmax. Sampled decode is deterministic under a fixed
        ``sampling.seed``.

        SPECULATIVE DECODING (``spec_k`` > 0, or ``RAY_TPU_SPEC_K``;
        paged engines only — the rewind substrate): each tick a cheap
        drafter proposes up to ``spec_k`` tokens per slot, one batched
        verify pass scores all k+1 positions through the same paged
        attention path, and per-slot acceptance commits a variable
        number of tokens — decode tokens per param-stream instead of
        one. ``drafter`` is a
        :class:`~ray_tpu.models.inference.SelfDrafter` (default: the
        target's first ``spec_draft_layers`` /
        ``RAY_TPU_SPEC_DRAFT_LAYERS`` layers over the target's own
        arena) or an
        :class:`~ray_tpu.models.inference.ExternalLlamaDrafter` (a
        separate small checkpoint with its own dense cache).
        ``spec_adaptive`` (default on; ``RAY_TPU_SPEC_ADAPTIVE``)
        ladders k from the windowed accept rate — down to 0, which
        dispatches the EXACT pre-spec tick program. Greedy outputs are
        bit-identical spec-on/off; sampled acceptance is rejection
        sampling that preserves the target distribution and replays
        deterministically across buffered rewinds.

        DISAGGREGATED ROLES (``role`` / ``RAY_TPU_SERVE_ROLE``; paged
        engines only): ``"prefill"`` runs admission + prefill and parks
        each request at its FIRST token with its arena blocks retained
        for :meth:`export_kv_payload` — the engine never decode-ticks,
        so a long prefill burst cannot stall anyone's TPOT.
        ``"decode"`` accepts :meth:`import_kv_payload` of an exported
        prefix (scattered into reserved blocks through the same
        table-scatter path prefill uses, indexed into the radix tree on
        arrival) and enters the decode tick directly. ``"both"`` (the
        default) is the colocated engine. Greedy outputs are
        bit-identical split vs colocated: the exported bytes are the
        exact arena blocks (int8 scales included) the colocated decode
        would have attended."""
        self.config = config
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token = eos_token
        self.sync_every = max(1, int(sync_every))
        self.sampling = SamplingParams.coerce(sampling)
        self.paged = _resolve_paged(paged)
        self.role = _resolve_role(role)
        if self.role != "both" and not self.paged:
            raise ValueError(
                "disaggregated prefill/decode roles need the paged KV "
                "plane (block-granular export/import); use paged=True "
                "or role='both'")
        self.block_size = int(block_size)
        if self.paged and (self.block_size < 8
                           or self.block_size & (self.block_size - 1)):
            # Prompt padding buckets are powers of two; a non-pow2 block
            # would make the padded length a non-multiple of the block
            # and break the prefill block reshape.
            raise ValueError(
                f"block_size must be a power of two >= 8, "
                f"got {self.block_size}")
        self.kv_dtype = resolve_kv_dtype(kv_dtype) if self.paged else None
        self.prefix_cache = self.paged and _resolve_prefix_cache(
            prefix_cache)
        self.use_decode_kernel = _resolve_decode_kernel(
            config, max_len, use_decode_kernel, paged=self.paged,
            block_size=self.block_size)
        # Speculative-decode knobs resolve BEFORE the arena is sized:
        # reservations carry spec_k look-ahead tokens (rejected draft
        # writes must land in already-reserved blocks), so max_blocks /
        # the default arena grow accordingly.
        self.spec_k = _resolve_spec_k(spec_k)
        self.drafter = drafter
        if self.spec_k:
            if not self.paged:
                raise ValueError(
                    "speculative decoding needs the paged KV plane (the "
                    "garbage-block rewind substrate); use paged=True or "
                    "spec_k=0")
            if self.drafter is None:
                self.drafter = SelfDrafter(spec_draft_layers)
            if self.drafter.external:
                if self.drafter.config.vocab_size != config.vocab_size:
                    raise ValueError(
                        "external drafter must share the target's "
                        "vocabulary")
                self.spec_draft_layers = self.drafter.config.num_layers
            else:
                self.spec_draft_layers = _resolve_spec_draft_layers(
                    spec_draft_layers
                    if spec_draft_layers is not None
                    else self.drafter.draft_layers, config.num_layers)
            if spec_adaptive is None:
                spec_adaptive = env_flag("RAY_TPU_SPEC_ADAPTIVE")
            self.spec_adaptive = (True if spec_adaptive is None
                                  else bool(spec_adaptive))
            self._spec_ladder_ks = _spec_ladder(self.spec_k)
        else:
            self.drafter = None
            self.spec_draft_layers = 0
            self.spec_adaptive = False
            self._spec_ladder_ks = []
        self._spec_cur_k = self.spec_k
        self._spec_ticks: Dict[int, Any] = {}   # ladder k -> compiled tick
        self._last_tick_k = 0                   # k the last tick ran with
        self._window_k = 0                      # k of the buffered window
        # Windowed accept-rate telemetry: (drafted, accepted) per applied
        # fetch — the adaptive-k controller and the accept-rate gauge both
        # read it.
        self._spec_window: deque = deque(
            maxlen=max(1, _env_int("RAY_TPU_SPEC_WINDOW", 128)))
        self._spec_probe_after = max(
            1, _env_int("RAY_TPU_SPEC_PROBE_TICKS", 256))
        self._spec_probe_countdown = self._spec_probe_after
        self.spec_draft_tokens = 0      # cumulative drafted
        self.spec_accepted_tokens = 0   # cumulative accepted by verify
        self.spec_tick_count = 0        # spec-tick dispatches
        self.base_tick_count = 0        # plain-tick dispatches
        self.decoded_tokens = 0         # committed decode tokens (bench)
        # Prefill accounting (bench_serve.py reads these; the metric
        # counters mirror them into the TSDB). With the prefix cache on,
        # ``prefill_tokens`` counts only NOVEL (suffix) tokens; the
        # hit/miss counters below split total prompt traffic.
        self.prefill_batches = 0
        self.prefill_requests = 0
        self.prefill_tokens = 0
        self.prefix_hit_tokens = 0      # prompt tokens served from cache
        self.prefix_miss_tokens = 0     # prompt tokens actually prefilled
        self.prefix_hit_requests = 0    # requests with >=1 matched block
        self.prefill_seconds = 0.0          # dispatch->first-token sync
        self._prefill_shapes: set = set()   # (N_pad, L_pad) compiled
        self._buf: List[Any] = []       # unstacked device token vectors
        self._pending: Optional[tuple] = None  # (stacked, [(slot, rid)])
        self.params = params if params is not None else llama.init_params(
            config, jax.random.PRNGKey(seed))
        # Weight-sync plane (ray_tpu/rl): monotone version of the live
        # params. 0 = the cold-start weights; every swap_params bumps it
        # and each request records the version that admitted it.
        self._weight_version = 0
        self._score_fn = None  # lazy teacher-forced logprob jit
        self.param_bytes = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(self.params))
        # Split out the non-layer params: a self-draft pass streams only
        # the truncated layer fraction plus the embed/norm/head — the
        # spec-aware tick_bytes_estimate prices drafts from these.
        self._head_param_bytes = sum(
            self.params[k].nbytes
            for k in ("embed", "final_norm", "lm_head"))
        self._layer_param_bytes = self.param_bytes - self._head_param_bytes
        self._draft_param_bytes = (
            sum(x.nbytes
                for x in jax.tree_util.tree_leaves(self.drafter.params))
            if self.spec_k and self.drafter.external else 0)
        self._draft_cache = None
        self.token_callback = token_callback
        if self.paged:
            # Table width covers max_len PLUS the spec look-ahead: a spec
            # tick writes draft/verify K/V up to position p + spec_k, and
            # those writes must stay inside the slot's own reservation
            # (the garbage redirect is for overrun PAST it).
            self.max_blocks = -(-(max_len + self.spec_k)
                                // self.block_size)
            self.num_blocks = int(
                num_blocks if num_blocks is not None
                else num_slots * self.max_blocks + 1)
            self.cache = PagedKVCache.create(
                config, self.num_blocks, self.block_size, self.kv_dtype)
            self.allocator = BlockAllocator(self.num_blocks)
            self._slot_blocks: Dict[int, List[int]] = {}
            # Radix index over block-aligned prompt chunks -> resident
            # arena blocks (None with the prefix cache off). Slots track
            # their pinned index nodes so release can deref instead of
            # freeing shared blocks.
            self._prefix = RadixBlockIndex() if self.prefix_cache else None
            self._slot_nodes: Dict[int, List[Any]] = {}
            self._d_tables = None
            self._d_limits = None
        else:
            self._prefix = None
            self._slot_nodes = {}
            self.cache = KVCache.create(config, num_slots, max_len)
        self._free: List[int] = list(range(num_slots))
        self._slots: Dict[int, Dict[str, Any]] = {}   # slot -> request
        # Device-resident decode state: last tokens + positions + the
        # sampling step counter live on the chip between ticks (uploaded
        # only when slot membership changes), so a steady decode tick
        # moves 4 bytes/slot host-ward and nothing device-ward.
        self._d_tokens = None
        self._d_positions = None
        self._d_step = None
        self._applied_steps = 0   # host mirror of the device step counter
        self._prefill_count = 0   # per-dispatch prefill sampling stream
        # Buffered-mode achieved-bandwidth window: wall time and tick
        # count between consecutive fetch syncs.
        self._bw_window_t0 = None
        self._bw_window_ticks = 0
        self._dirty = True
        self._waiting: deque = deque()
        self._rid = itertools.count()
        self._finished: Dict[int, List[int]] = {}
        # Disaggregation state: prefill-role engines park each request's
        # retained arena blocks here between its first token and the
        # export call; decode-role engines hold pre-reserved import
        # blocks (the router reserves the decode slot BEFORE dispatching
        # prefill so the payload never arrives to a full arena).
        self._handoff_ready: Dict[int, Dict[str, Any]] = {}
        self._import_reservations: Dict[int, Dict[str, Any]] = {}
        self._reservation_ids = itertools.count()
        self.handoff_exports = 0    # payloads exported (bench/tests)
        self.handoff_imports = 0    # payloads imported (bench/tests)
        # Request-path telemetry: one lifecycle record per live request
        # (submit/admit/prefill/first-token/finish timestamps + the
        # caller's trace context). TTFT decomposition histograms are
        # always on (host bookkeeping only); per-window decode spans are
        # recorded only for traced requests, so with tracing disabled
        # the decode loop pays one integer check per fetch.
        self._req_meta: Dict[int, Dict[str, Any]] = {}
        self._traced_live = 0            # live requests carrying a trace
        self._window_t0: Optional[float] = None  # decode-window start
        self.request_breakdowns: deque = deque(maxlen=4096)
        self._MAX_WINDOWS = 64           # per-request span cap (tail merges)
        # Observability: engine label for the slot-occupancy / decode-rate
        # series (continuous-batching is the serving hot loop the decode
        # roofline work tunes — the TSDB needs its history). The instance
        # counter keeps co-resident engines' series from colliding.
        self._mtags = {"engine":
                       f"slots{num_slots}-{next(self._engine_ids)}"}
        cfg = config

        use_kernel = self.use_decode_kernel
        sampling_cfg = self.sampling
        block_size_c = self.block_size

        # The XLA monitor dispatches per signature and audits shape
        # growth: prefill's signatures are pow-2 bucketed in N and L by
        # design (allowed caps included — max_len/num_slots/block counts
        # need not be powers of two), so legitimate bucket growth stays
        # silent while a stray odd shape raises
        # ray_tpu_xla_retraces_total. The tick has exactly ONE legitimate
        # signature.
        prefill_dims = (max_len, num_slots)
        if self.paged:
            # Prefix-aware suffix groups add legitimate non-pow2 dims:
            # suffix buckets clamped to the table capacity left after a
            # matched prefix. Matched-block counts themselves bucket to
            # powers of two in admission (_bucket_floor) — already
            # silent under the bucketed policy — so the clamp takes
            # only log-many values, and this whitelist ENFORCES that
            # bound: an exact-m regression would raise
            # ray_tpu_xla_retraces_total.
            ms = {0}
            m = 1
            while m <= self.max_blocks:
                ms.add(m)
                m *= 2
            prefill_dims += (0,)
            prefill_dims += tuple(self.block_size * (self.max_blocks - v)
                                  for v in sorted(ms))

        if self.paged:
            @xla_monitor.instrument(name="cb_prefill",
                                    shape_policy="bucketed",
                                    allowed_dims=prefill_dims,
                                    donate_argnums=(2,))
            def prefill(params, tokens, cache, ptables, tables_w,
                        last_idx, pstep):
                # BATCHED BUCKETED PREFILL, paged + prefix-aware: tokens
                # [N, S] holds N same-group SUFFIXES (prompt tokens not
                # covered by matched prefix blocks; the whole prompt
                # when nothing matched); ``ptables`` [N, m] names the
                # shared arena blocks holding each row's m-block prefix
                # (READ-ONLY — gathered, dequantized when int8, never
                # written); ``tables_w`` [N, S // bs] names the blocks
                # the suffix K/V land in (overflow entries point at the
                # garbage block). Only N first tokens leave the device.
                n, s_pad = tokens.shape
                m = ptables.shape[1]
                positions = m * block_size_c + jnp.arange(s_pad)
                flat_p = ptables.reshape(-1)                 # [N * m]
                pk = cache.k[:, flat_p]
                pv = cache.v[:, flat_p]
                if cache.quantized:
                    pk = (pk.astype(jnp.float32)
                          * cache.k_scale[:, flat_p][..., None]
                          ).astype(cfg.dtype)
                    pv = (pv.astype(jnp.float32)
                          * cache.v_scale[:, flat_p][..., None]
                          ).astype(cfg.dtype)

                def to_ctx(a):
                    # [Lyr, N*m, bs, ...] -> [Lyr, N, m*bs, ...]
                    return a.reshape(a.shape[0], n, m * block_size_c,
                                     *a.shape[3:])

                logits, stored = _prefill_forward_paged(
                    params, tokens, positions,
                    to_ctx(pk.astype(cfg.dtype)),
                    to_ctx(pv.astype(cfg.dtype)),
                    cfg, cache.quantized)
                npb = s_pad // block_size_c
                flat_tables = tables_w.reshape(-1)           # [N * npb]

                def to_blocks(a):
                    # [Lyr, N, S, ...] -> [Lyr, N*npb, bs, ...]
                    return a.reshape(a.shape[0], n * npb, block_size_c,
                                     *a.shape[3:])

                if cache.quantized:
                    kq, vq, ksc, vsc = stored
                    new_cache = PagedKVCache(
                        k=cache.k.at[:, flat_tables].set(to_blocks(kq)),
                        v=cache.v.at[:, flat_tables].set(to_blocks(vq)),
                        k_scale=cache.k_scale.at[:, flat_tables].set(
                            to_blocks(ksc)),
                        v_scale=cache.v_scale.at[:, flat_tables].set(
                            to_blocks(vsc)))
                else:
                    k_s, v_s = stored
                    dt = cache.k.dtype
                    new_cache = PagedKVCache(
                        k=cache.k.at[:, flat_tables].set(
                            to_blocks(k_s.astype(dt))),
                        v=cache.v.at[:, flat_tables].set(
                            to_blocks(v_s.astype(dt))))
                last = jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)  # [N, 1, V]
                first = _next_tokens(last, pstep, sampling_cfg,
                                     salt=_PREFILL_SALT)
                return first, new_cache

            @xla_monitor.instrument(name="cb_tick", donate_argnums=(5,))
            def tick(params, tokens, positions, tables, limits, cache,
                     step):
                return _decode_tick_paged(params, tokens, positions,
                                          tables, limits, cache, step,
                                          cfg, use_kernel=use_kernel,
                                          sampling=sampling_cfg)
        else:
            @xla_monitor.instrument(name="cb_prefill",
                                    shape_policy="bucketed",
                                    allowed_dims=prefill_dims,
                                    donate_argnums=(2,))
            def prefill(params, tokens, cache, slots, last_idx, pstep):
                # BATCHED BUCKETED PREFILL: tokens [N, L] holds N
                # same-bucket prompts destined for KV slots ``slots``
                # [N]; ``last_idx`` [N] is each prompt's true_len - 1.
                # Slot gather + write-back live INSIDE the jit with the
                # pooled cache donated, so an admission burst is one
                # in-place program, not N whole-cache copies. Only the N
                # first tokens leave the device (selection on chip), not
                # [N, L, V] logits.
                positions = jnp.arange(tokens.shape[1])
                slot_cache = KVCache(k=jnp.take(cache.k, slots, axis=1),
                                     v=jnp.take(cache.v, slots, axis=1))
                logits, sc = _forward_cached(params, tokens, positions,
                                             slot_cache, cfg)
                cache = KVCache(k=cache.k.at[:, slots].set(sc.k),
                                v=cache.v.at[:, slots].set(sc.v))
                last = jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)  # [N, 1, V]
                first = _next_tokens(last, pstep, sampling_cfg,
                                     salt=_PREFILL_SALT)
                return first, cache

            @xla_monitor.instrument(name="cb_tick", donate_argnums=(3,))
            def tick(params, tokens, positions, cache, step):
                return _decode_tick(params, tokens, positions, cache,
                                    step, cfg, use_kernel=use_kernel,
                                    sampling=sampling_cfg)

        self._prefill = prefill
        self._tick = tick

        if self.spec_k and self.drafter.external:
            # The external drafter keeps its own dense per-slot cache;
            # admission prefills the FULL prompt into it (the target's
            # prefix cache shortens only the target's prefill), decode
            # advances it inside the spec tick. No sampling: first
            # tokens come from the target's prefill.
            dcfg = self.drafter.config
            self._draft_cache = KVCache.create(dcfg, num_slots, max_len)

            @xla_monitor.instrument(name="cb_draft_prefill",
                                    shape_policy="bucketed",
                                    allowed_dims=prefill_dims,
                                    donate_argnums=(2,))
            def draft_prefill(dparams, tokens, dcache, slots):
                positions = jnp.arange(tokens.shape[1])
                slot_cache = KVCache(
                    k=jnp.take(dcache.k, slots, axis=1),
                    v=jnp.take(dcache.v, slots, axis=1))
                _, sc = _forward_cached(dparams, tokens, positions,
                                        slot_cache, dcfg)
                return KVCache(k=dcache.k.at[:, slots].set(sc.k),
                               v=dcache.v.at[:, slots].set(sc.v))

            self._draft_prefill = draft_prefill
        else:
            self._draft_prefill = None

    def _get_spec_tick(self, k: int):
        """Compiled spec-tick program for ladder rung ``k`` (memoized:
        one program per rung, all named cb_spec_tick). The window dims
        k+1 for every rung join the bucketed whitelist so legitimate
        ladder moves never raise ray_tpu_xla_retraces_total — the same
        prefill_dims discipline the admission path uses."""
        tick = self._spec_ticks.get(k)
        if tick is not None:
            return tick
        cfg = self.config
        use_kernel = self.use_decode_kernel
        sampling_cfg = self.sampling
        n_draft = self.spec_draft_layers
        spec_dims = (self.max_len, self.num_slots, self.max_blocks)
        spec_dims += tuple(kk + 1 for kk in self._spec_ladder_ks)
        if self.drafter.external:
            dcfg = self.drafter.config

            @xla_monitor.instrument(name="cb_spec_tick",
                                    shape_policy="bucketed",
                                    allowed_dims=spec_dims,
                                    donate_argnums=(5, 6))
            def spec_tick(params, tokens, positions, tables, limits,
                          cache, dcache, step, dparams):
                return _spec_tick_paged(
                    params, tokens, positions, tables, limits, cache,
                    step, cfg, k, n_draft, use_kernel, sampling_cfg,
                    draft_params=dparams, draft_cache=dcache,
                    draft_config=dcfg)
        else:
            @xla_monitor.instrument(name="cb_spec_tick",
                                    shape_policy="bucketed",
                                    allowed_dims=spec_dims,
                                    donate_argnums=(5,))
            def spec_tick(params, tokens, positions, tables, limits,
                          cache, step):
                return _spec_tick_paged(
                    params, tokens, positions, tables, limits, cache,
                    step, cfg, k, n_draft, use_kernel, sampling_cfg)

        self._spec_ticks[k] = spec_tick
        return spec_tick

    def prefill_cache_misses(self) -> int:
        """Compiled prefill program count (one per (N, bucket) shape) —
        the admission-burst acceptance check reads this. Prefers jax's
        real jit-cache counter (private API); falls back to the shapes
        this engine dispatched if a jax upgrade drops it."""
        cache_size = getattr(self._prefill, "_cache_size", None)
        if cache_size is not None:
            return cache_size()
        return len(self._prefill_shapes)

    # ------------------------------------------ request-path telemetry
    def _req_tags(self, rec: Dict[str, Any]) -> Dict[str, str]:
        t = rec.get("trace") or {}
        return {"deployment": str(t.get("deployment", "")),
                "tenant": str(t.get("tenant", "")),
                "engine": self._mtags["engine"],
                "role": self.role}

    def _span_common(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        t = rec.get("trace") or {}
        return {"trace_id": t.get("trace_id", ""),
                "parent_span_id": t.get("parent_span_id", ""),
                "kind": "engine",
                "request_id": t.get("request_id", ""),
                "rid": rec["rid"]}

    def _note_first_token(self, rec: Dict[str, Any], prefill_t0: float,
                          first_tok_ts: float) -> None:
        """First token just landed for ``rec``'s request: close the TTFT
        decomposition (queue -> arena-wait -> prefill) and emit the
        component histograms + spans. By construction the components sum
        to TTFT up to the admission loop's group-assembly gap."""
        from ray_tpu._private import metrics_defs as mdefs

        blocked = rec.get("arena_blocked",
                          rec.get("admit", rec["submit"]))
        admit = rec.get("admit", blocked)
        rec["first_token"] = first_tok_ts
        rec["queue_s"] = max(blocked - rec["submit"], 0.0)
        rec["arena_wait_s"] = max(admit - blocked, 0.0)
        rec["prefill_s"] = max(first_tok_ts - prefill_t0, 0.0)
        rec["ttft_s"] = max(first_tok_ts - rec["submit"], 0.0)
        tags = self._req_tags(rec)
        mdefs.SERVE_REQ_TTFT.observe(rec["ttft_s"], tags=tags)
        mdefs.SERVE_REQ_QUEUE.observe(rec["queue_s"], tags=tags)
        mdefs.SERVE_REQ_ARENA_WAIT.observe(rec["arena_wait_s"], tags=tags)
        mdefs.SERVE_REQ_PREFILL.observe(rec["prefill_s"], tags=tags)
        if rec["traced"]:
            common = self._span_common(rec)
            tracing.emit_span("engine.queue", ts=rec["submit"],
                              dur=rec["queue_s"], **common)
            if rec["arena_wait_s"] > 0:
                tracing.emit_span("engine.arena_wait", ts=blocked,
                                  dur=rec["arena_wait_s"],
                                  blocks=rec.get("blocks", 0), **common)
            tracing.emit_span("engine.prefill", ts=prefill_t0,
                              dur=rec["prefill_s"],
                              prompt_tokens=rec["prompt_len"], **common)

    def _finish_request(self, rid: int, outcome: str,
                        tokens: int = 0) -> None:
        """Terminal lifecycle edge (finished / evicted / aborted): emit
        TPOT + outcome metrics, the request's decode-window spans, and
        push a breakdown record for bench/CLI consumers."""
        rec = self._req_meta.pop(rid, None)
        if rec is None:
            return
        from ray_tpu._private import metrics_defs as mdefs

        now = time.time()
        if rec["traced"]:
            self._traced_live -= 1
        tags = self._req_tags(rec)
        mdefs.SERVE_REQ_OUTCOMES.inc(tags={**tags, "outcome": outcome})
        tpot = None
        first = rec.get("first_token")
        if first is not None and tokens > 1:
            # ``tokens`` is the COMMITTED count, not the tick count — a
            # spec tick that lands 3 tokens divides the same wall time by
            # 3, so TPOT stays honest under multi-token ticks.
            tpot = max(now - first, 0.0) / (tokens - 1)
            mdefs.SERVE_REQ_TPOT.observe(tpot, tags=tags)
        trace = rec.get("trace") or {}
        self.request_breakdowns.append({
            "rid": rid, "outcome": outcome, "tokens": tokens,
            "queue_s": rec.get("queue_s"),
            "arena_wait_s": rec.get("arena_wait_s"),
            "prefill_s": rec.get("prefill_s"),
            "ttft_s": rec.get("ttft_s"), "tpot_s": tpot,
            "prefix_tokens": rec.get("prefix_tokens", 0),
            "prompt_tokens": rec.get("prompt_len", 0),
            "weight_version": rec.get("weight_version"),
            "trace_id": trace.get("trace_id"),
            "request_id": trace.get("request_id"),
            # Disaggregated imports carry the handoff latency split
            # (export_s / channel_s / import_s) — bench_serve's
            # disagg_phase sums these against the handoff wall.
            "handoff": rec.get("handoff")})
        if not rec["traced"]:
            return
        common = self._span_common(rec)
        if first is None:
            # Evicted/aborted before admission completed: the queue span
            # (normally closed at first token) still needs to exist for
            # the trace to show where the request died.
            tracing.emit_span("engine.queue", ts=rec["submit"],
                              dur=max(now - rec["submit"], 0.0),
                              outcome=outcome, **common)
        for i, (w0, w1, n) in enumerate(rec.get("windows", ())):
            tracing.emit_span("engine.decode_window", ts=w0,
                              dur=max(w1 - w0, 0.0), tokens=n,
                              window=i, **common)
        tail = rec.get("window_tail")
        if tail is not None:
            tracing.emit_span("engine.decode_tail", ts=tail[0],
                              dur=max(tail[1] - tail[0], 0.0),
                              tokens=tail[2], windows=tail[3], **common)
        tracing.emit_span(f"engine.{outcome}", ts=now, dur=0.0,
                          tokens=tokens, **common)

    def pressure_snapshot(self) -> Dict[str, Any]:
        """Live engine pressure — the router/autoscaler input: queue
        depth, slot occupancy, free KV arena blocks, and the prefill
        token backlog still waiting for admission."""
        free_blocks = self.allocator.free_count if self.paged else 0
        cached = (self._prefix.cached_count
                  if self.paged and self._prefix is not None else 0)
        return {
            "queue_depth": len(self._waiting),
            "active_slots": len(self._slots),
            "num_slots": self.num_slots,
            "kv_blocks_free": free_blocks,
            # Reclaimable-on-demand prefix blocks: admission-available
            # capacity is free + cached, which the router/shedding
            # thresholds should use instead of raw free.
            "kv_blocks_cached": cached,
            "kv_blocks_total": (self.num_blocks - 1 if self.paged else 0),
            # Draft look-ahead blocks are RESERVED capacity (the
            # allocator already excludes them from kv_blocks_free — no
            # phantom free arena for the admission gate or the arbiter
            # SLO guard); this reports how much of the reservation is
            # speculative head-room rather than committed tokens.
            "kv_blocks_spec_lookahead": sum(
                st.get("la_blocks", 0) for st in self._slots.values()),
            "inflight_prefill_tokens": sum(
                len(r["prompt"]) for r in self._waiting),
            # Role-aware fields (disaggregated prefill/decode): the
            # router classifier and the autoscaler/arbiter read these so
            # prefill and decode fleets scale independently.
            "role": self.role,
            # Prompt tokens queued for admission PLUS parked exports —
            # a prefill fleet's backlog is both.
            "prefill_queue_tokens": (
                sum(len(r["prompt"]) for r in self._waiting)
                + sum(len(e["prompt"])
                      for e in self._handoff_ready.values())),
            # Arena capacity an import could land in right now: free
            # blocks plus LRU-cached ones _alloc_blocks would reclaim.
            "kv_blocks_importable": free_blocks + cached,
            "handoff_ready": len(self._handoff_ready),
            "import_reservations": len(self._import_reservations),
        }

    # ---------------------------------------------------------------- api
    @property
    def weight_version(self) -> int:
        """Monotone version of the live params (0 = cold-start)."""
        return self._weight_version

    def swap_params(self, params, version: Optional[int] = None) -> int:
        """Replace the live params between ticks — the ONLY sanctioned
        post-init assignment of ``self.params`` (a tick-boundary source
        lint enforces this). The caller must hold the engine's tick
        exclusion (the serve deployment swaps under its engine lock, so
        no compiled tick is in flight); the next ``_run_tick`` dispatch
        reads the fresh tree. The KV cache and every in-flight request's
        device state are untouched: in-flight generations continue
        un-dropped under the new weights.

        The new tree must match the old one structurally (same treedef,
        same leaf shapes/dtypes) — the compiled tick programs were traced
        against that signature and a silent mismatch would either retrace
        per swap or miscompute. Returns the new weight version
        (``version`` or the previous one + 1)."""
        import jax

        old_leaves, old_treedef = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_treedef = jax.tree_util.tree_flatten(params)
        if new_treedef != old_treedef:
            raise ValueError(
                f"swap_params treedef mismatch: engine was built with "
                f"{old_treedef}, swap brought {new_treedef}")
        for i, (old, new) in enumerate(zip(old_leaves, new_leaves)):
            if old.shape != new.shape or old.dtype != new.dtype:
                raise ValueError(
                    f"swap_params leaf {i} mismatch: engine has "
                    f"{old.shape}/{old.dtype}, swap brought "
                    f"{new.shape}/{new.dtype}")
        self.params = params
        self._weight_version = (int(version) if version is not None
                                else self._weight_version + 1)
        return self._weight_version

    def score_logprobs(self, prompt_tokens: List[int],
                       out_tokens: List[int]) -> np.ndarray:
        """Per-token behavior logprobs of ``out_tokens`` given
        ``prompt_tokens``, under the CURRENT live params — one
        teacher-forced forward through the same model the decode ticks
        run, so the RL experience path's importance ratios are priced
        against the true generating policy. Returns ``[len(out_tokens)]``
        float32."""
        if not out_tokens:
            return np.zeros((0,), np.float32)
        if self._score_fn is None:
            cfg = self.config

            @xla_monitor.instrument(name="cb_score",
                                    shape_policy="bucketed",
                                    allowed_dims=(1, self.max_len))
            def score(params, tokens):
                logits = llama.forward(params, tokens, cfg)
                return jax.nn.log_softmax(logits.astype(jnp.float32))

            self._score_fn = score
        full = list(prompt_tokens) + list(out_tokens)
        if len(full) > self.max_len:
            raise ValueError(
                f"score_logprobs sequence ({len(full)} tokens) exceeds "
                f"max_len={self.max_len}")
        pad = min(_bucket(len(full)), self.max_len)
        arr = np.zeros((1, pad), np.int32)
        arr[0, :len(full)] = full
        logp_all = np.asarray(self._score_fn(self.params,
                                             jnp.asarray(arr)))[0]
        start = len(prompt_tokens)
        idx = np.arange(start - 1, start - 1 + len(out_tokens))
        return logp_all[idx, np.asarray(out_tokens)].astype(np.float32)

    def submit(self, prompt_tokens: List[int],
               max_new_tokens: int = 32,
               trace: Optional[Dict[str, Any]] = None) -> int:
        """Queue a request; returns its id. It joins the next tick with a
        free slot — no waiting for the current batch to drain.

        ``trace`` carries the serve request context
        (``request_id``/``trace_id``/``parent_span_id``/``deployment``/
        ``tenant``): lifecycle spans (queue, arena-wait, prefill, decode
        windows) are emitted into that trace when ``RAY_TPU_TRACING=1``,
        and the TTFT/TPOT histograms are tagged with its
        deployment/tenant either way."""
        assert len(prompt_tokens) + max_new_tokens <= self.max_len
        if max_new_tokens <= 0:
            # Nothing to generate: finish immediately — no slot, no
            # blocks, so arena capacity is irrelevant.
            rid = next(self._rid)
            self._finished[rid] = []
            return rid
        if self.paged and self._blocks_needed(
                len(prompt_tokens), max_new_tokens) > self.num_blocks - 1:
            # A reservation larger than the whole arena can NEVER be
            # satisfied: admitting it to the queue would wedge the FIFO
            # head (and every request behind it) forever.
            raise ValueError(
                f"request needs more KV blocks than the arena holds "
                f"({self._blocks_needed(len(prompt_tokens), max_new_tokens)}"
                f" > {self.num_blocks - 1}); raise num_blocks or shorten "
                f"the request")
        rid = next(self._rid)
        traced = trace is not None and tracing.enabled()
        self._req_meta[rid] = {
            "rid": rid, "submit": time.time(),
            "prompt_len": len(prompt_tokens),
            "weight_version": self._weight_version,
            "trace": trace, "traced": traced, "windows": []}
        if traced:
            self._traced_live += 1
        self._waiting.append({"rid": rid,
                              "prompt": list(prompt_tokens),
                              "max_new": max_new_tokens})
        return rid

    def _release_slot(self, slot: int) -> None:
        self._free.append(slot)
        if self.paged:
            blocks = self._slot_blocks.pop(slot, None)
            nodes = self._slot_nodes.pop(slot, None)
            if nodes:
                # Indexed (shared/shareable) blocks: deref — refcount 0
                # parks them in the LRU "cached" state instead of the
                # free list, so a later prefix match revives them and
                # arena pressure reclaims them before admission blocks.
                self._prefix.release(nodes)
                shared = {nd.block for nd in nodes}
                blocks = [b for b in (blocks or []) if b not in shared]
            if blocks:
                self.allocator.free(blocks)

    def cancel(self, rid: int) -> bool:
        """Drop a request (client disconnected): frees its slot / queue
        spot so abandoned generations stop burning decode ticks."""
        for i, req in enumerate(self._waiting):
            if req["rid"] == rid:
                del self._waiting[i]
                self._finish_request(rid, "evicted")
                return True
        for slot, st in list(self._slots.items()):
            if st["rid"] == rid:
                del self._slots[slot]
                self._release_slot(slot)
                self._dirty = True
                self._finish_request(rid, "evicted",
                                     tokens=len(st["out"]))
                return True
        # A parked handoff's retained blocks must not outlive the
        # request (the first token already sits in _finished).
        self.abandon_handoff(rid)
        return self._finished.pop(rid, None) is not None

    def reset(self) -> List[int]:
        """Abort everything (recovery after an engine error). Returns the
        request ids that were dropped."""
        dropped = [st["rid"] for st in self._slots.values()]
        dropped += [r["rid"] for r in self._waiting]
        tokens_by_rid = {st["rid"]: len(st["out"])
                         for st in self._slots.values()}
        for rid in dropped:
            self._finish_request(rid, "aborted",
                                 tokens=tokens_by_rid.get(rid, 0))
        self._req_meta.clear()
        self._traced_live = 0
        self._window_t0 = None
        self._slots.clear()
        self._waiting.clear()
        self._free = list(range(self.num_slots))
        self._finished.clear()
        self._buf = []
        self._pending = None
        # Parked handoffs and import reservations die with the arena
        # (allocator.reset below reclaims their blocks wholesale).
        self._handoff_ready.clear()
        self._import_reservations.clear()
        # The prefill/tick jits donate the pooled cache; after a mid-step
        # failure the old buffers may already be deleted, so rebuild the
        # pool or every later step would raise "Array has been deleted".
        if self.paged:
            self.cache = PagedKVCache.create(
                self.config, self.num_blocks, self.block_size,
                self.kv_dtype)
            self.allocator.reset()
            self._slot_blocks.clear()
            self._slot_nodes.clear()
            if self._prefix is not None:
                # The rebuilt arena holds zeros: every cached prefix
                # entry would alias garbage, so the index restarts cold.
                self._prefix.clear()
        else:
            self.cache = KVCache.create(self.config, self.num_slots,
                                        self.max_len)
        self._applied_steps = 0
        self._bw_window_t0 = None
        self._bw_window_ticks = 0
        # Spec state restarts with the engine: the controller re-enters at
        # the configured k and the external drafter's dense cache (donated
        # by the spec tick like the main arena) is rebuilt alongside it.
        self._spec_cur_k = self.spec_k
        self._spec_window.clear()
        self._spec_probe_countdown = self._spec_probe_after
        self._window_k = 0
        if self._draft_cache is not None:
            self._draft_cache = KVCache.create(
                self.drafter.config, self.num_slots, self.max_len)
        self._dirty = True
        return dropped

    @property
    def active_count(self) -> int:
        return len(self._slots)

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admitted prompt tokens served from cached prefix
        blocks (0.0 with the prefix cache off or before any admission)."""
        total = self.prefix_hit_tokens + self.prefix_miss_tokens
        return self.prefix_hit_tokens / total if total else 0.0

    def has_work(self) -> bool:
        return bool(self._slots or self._waiting or self._finished
                    or self._buf or self._pending)

    # --------------------------------------------- disaggregated handoff
    def _park_for_handoff(self, slot: int, req: Dict[str, Any]) -> None:
        """Prefill-role terminal edge: the request just produced its
        first token — free the SLOT (the next admission group can use
        it) but retain the arena blocks until :meth:`export_kv_payload`
        ships them. The first token joins ``_finished`` so the serving
        layer observes it through the normal step() results."""
        st = self._slots.pop(slot, None)
        if st is None:
            return  # finished at the first token: nothing to hand off
        rid = st["rid"]
        self._free.append(slot)
        self._handoff_ready[rid] = {
            "prompt": list(req["prompt"]),
            "first": st["out"][0],
            "max_new": st["max_new"],
            "blocks": self._slot_blocks.pop(slot, []),
            "nodes": self._slot_nodes.pop(slot, []),
        }
        self._finished[rid] = list(st["out"])
        self._finish_request(rid, "prefilled", tokens=len(st["out"]))
        self._dirty = True

    def _release_handoff_blocks(self, entry: Dict[str, Any]) -> None:
        """Return a parked handoff's blocks to the arena. Indexed blocks
        deref into the LRU "cached" state (a resubmitted twin re-matches
        them instead of re-prefilling), exclusives free outright."""
        blocks, nodes = entry["blocks"], entry["nodes"]
        if nodes:
            self._prefix.release(nodes)
            shared = {nd.block for nd in nodes}
            blocks = [b for b in blocks if b not in shared]
        if blocks:
            self.allocator.free(blocks)

    def handoff_ready(self) -> List[int]:
        """Request ids parked with exported-ready KV (prefill role)."""
        return list(self._handoff_ready)

    def abandon_handoff(self, rid: int) -> bool:
        """Drop a parked handoff without exporting (client gone, or the
        decode side never came for it): frees the retained blocks."""
        entry = self._handoff_ready.pop(rid, None)
        if entry is None:
            return False
        self._release_handoff_blocks(entry)
        return True

    def export_kv_payload(self, rid: int) -> Dict[str, Any]:
        """Materialize a parked request's KV handoff: gather its
        prompt-covering arena blocks (K/V plus int8 scale sidecars) to
        host as ZERO-COPY VIEWS of one contiguous staging buffer, with
        a crc32 manifest over the staging bytes. Only the
        ``ceil(prompt/block_size)`` prompt blocks ship — the decode side
        sizes its own reservation for the full generation — and the
        retained blocks release on return (indexed ones park in the
        LRU, so a resubmit after a lost transfer re-matches them).

        Call through ``ray_tpu.serve.kv_transfer`` — the journal-gated
        helper every cross-replica transfer must ride (a source lint
        pins this)."""
        if self.role == "decode":
            raise ValueError("decode-role engines do not export KV")
        entry = self._handoff_ready.pop(rid, None)
        if entry is None:
            raise KeyError(
                f"request {rid} has no handoff-ready KV (not prefilled "
                f"by a prefill-role engine, or already exported)")
        prompt = entry["prompt"]
        nb = -(-len(prompt) // self.block_size)
        blocks = list(entry["blocks"][:nb])
        staging, layout = self.cache.gather_blocks(blocks)
        payload = {
            "version": HANDOFF_MANIFEST_VERSION,
            "rid": rid,
            "prompt": prompt,
            "chunks": prompt_chunks(prompt, self.block_size),
            "first_token": int(entry["first"]),
            "max_new": int(entry["max_new"]),
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
            "num_layers": self.config.num_layers,
            "num_kv_heads": self.config.num_kv_heads,
            "head_dim": self.config.head_dim,
            "num_blocks": nb,
            "layout": layout,
            "staging": staging,
            "nbytes": int(staging.nbytes),
            "crc32": zlib.crc32(staging),
        }
        self._release_handoff_blocks(entry)
        self.handoff_exports += 1
        return payload

    def reserve_import(self, prompt_len: int,
                       max_new: int) -> Optional[int]:
        """Pre-reserve the arena blocks a future import will need (the
        router reserves the decode slot BEFORE dispatching prefill, so
        the payload never races arena pressure on arrival). Returns a
        reservation id, or None when the arena cannot cover it."""
        if self.role == "prefill":
            raise ValueError("prefill-role engines do not import KV")
        self.sweep_reservations()
        got = self._alloc_blocks(self._blocks_needed(prompt_len, max_new))
        if got is None:
            return None
        res_id = next(self._reservation_ids)
        self._import_reservations[res_id] = {
            "blocks": got, "prompt_len": prompt_len, "max_new": max_new,
            "ts": time.monotonic()}
        return res_id

    def sweep_reservations(self, ttl_s: Optional[float] = None) -> int:
        """Expire import reservations whose handoff never arrived (the
        router's reserve and decode dispatch landed on different
        replicas, or the prefill side died before exporting) — a stale
        ticket must not pin arena blocks forever. TTL from
        ``RAY_TPU_KV_RESERVE_TTL_S`` (default 30s)."""
        if not self._import_reservations:
            return 0
        if ttl_s is None:
            ttl_s = float(os.environ.get("RAY_TPU_KV_RESERVE_TTL_S",
                                         "30"))
        cutoff = time.monotonic() - ttl_s
        stale = [r for r, ent in self._import_reservations.items()
                 if ent.get("ts", 0.0) < cutoff]
        for res_id in stale:
            self.allocator.free(
                self._import_reservations.pop(res_id)["blocks"])
        return len(stale)

    def cancel_reservation(self, res_id: int) -> bool:
        """Release a pre-reservation (prefill died and the request is
        resubmitting elsewhere, or the client disconnected)."""
        ent = self._import_reservations.pop(res_id, None)
        if ent is None:
            return False
        self.allocator.free(ent["blocks"])
        return True

    def import_kv_payload(self, payload: Dict[str, Any],
                          reservation: Optional[int] = None,
                          trace: Optional[Dict[str, Any]] = None,
                          breakdown: Optional[Dict[str, float]] = None
                          ) -> int:
        """Land an exported KV payload in THIS engine's arena and enter
        decode directly: crc-verify the staging bytes, scatter them into
        reserved blocks through the same table-scatter path prefill
        uses, insert the transferred prefix into the radix index
        (shareable immediately, read-only refcounted like any matched
        prefix), and create a live decode slot continuing from the
        prefill's first token. Greedy decode from here is bit-identical
        to the colocated engine: the imported bytes ARE the blocks the
        colocated decode would have attended.

        Returns the LOCAL request id (the import is a fresh request on
        this engine's id stream). Call through
        ``ray_tpu.serve.kv_transfer`` — the journal-gated helper every
        cross-replica transfer must ride (a source lint pins this)."""
        if self.role == "prefill":
            raise ValueError("prefill-role engines do not import KV")
        if payload.get("version") != HANDOFF_MANIFEST_VERSION:
            raise ValueError(
                f"KV handoff version mismatch: payload "
                f"v{payload.get('version')}, engine expects "
                f"v{HANDOFF_MANIFEST_VERSION}")
        staging = payload["staging"]
        crc = zlib.crc32(staging)
        if crc != payload["crc32"]:
            raise ValueError(
                f"KV handoff crc mismatch (got {crc:#010x}, manifest "
                f"says {payload['crc32']:#010x}): payload corrupted in "
                f"transit")
        for field, mine in (("block_size", self.block_size),
                            ("kv_dtype", self.kv_dtype),
                            ("num_layers", self.config.num_layers),
                            ("num_kv_heads", self.config.num_kv_heads),
                            ("head_dim", self.config.head_dim)):
            if payload[field] != mine:
                raise ValueError(
                    f"KV handoff geometry mismatch on {field}: payload "
                    f"{payload[field]!r} vs engine {mine!r}")
        t0 = time.time()
        prompt = list(payload["prompt"])
        plen = len(prompt)
        max_new = int(payload["max_new"])
        if plen + max_new > self.max_len:
            raise ValueError(
                f"imported request ({plen}+{max_new} tokens) exceeds "
                f"this engine's max_len={self.max_len}")
        need = self._blocks_needed(plen, max_new)
        blocks: Optional[List[int]] = None
        if reservation is not None:
            ent = self._import_reservations.pop(reservation, None)
            if ent is not None:
                if len(ent["blocks"]) >= need:
                    blocks = ent["blocks"][:need]
                    if ent["blocks"][need:]:
                        self.allocator.free(ent["blocks"][need:])
                else:
                    # Reservation was sized for a different request:
                    # return it and fall through to a fresh grab.
                    self.allocator.free(ent["blocks"])
        if blocks is None:
            blocks = self._alloc_blocks(need)
        if blocks is None:
            raise RuntimeError(
                f"decode arena cannot cover the import ({need} blocks "
                f"needed, {self.allocator.free_count} free); reserve "
                f"ahead with reserve_import")
        if not self._free:
            self.allocator.free(blocks)
            raise RuntimeError("no free decode slot for the import")
        nb = int(payload["num_blocks"])
        self.cache = self.cache.scatter_blocks(
            blocks[:nb], payload["staging"], payload["layout"])
        rid = next(self._rid)
        traced = trace is not None and tracing.enabled()
        meta = {
            "rid": rid, "submit": t0, "prompt_len": plen,
            "weight_version": self._weight_version,
            "trace": trace, "traced": traced, "windows": [],
            "admit": t0, "blocks": len(blocks),
            "prefix_tokens": plen,  # the whole prompt arrived prefilled
        }
        if breakdown:
            meta["handoff"] = dict(breakdown)
        self._req_meta[rid] = meta
        if traced:
            self._traced_live += 1
        slot = self._free.pop()
        self._slot_blocks[slot] = blocks
        if self._prefix is not None and payload["chunks"]:
            created = self._prefix.insert(
                [tuple(c) for c in payload["chunks"]], blocks)
            if created:
                self._slot_nodes[slot] = created
        first = int(payload["first_token"])
        now = time.time()
        self._note_first_token(meta, t0, now)
        if meta.get("handoff") is not None:
            meta["handoff"]["import_s"] = now - t0
        if self.token_callback is not None:
            self.token_callback(rid, first)
        self._slots[slot] = {
            "rid": rid, "out": [first], "max_new": max_new,
            "pos": plen, "last": first,
            "la_blocks": self._lookahead_blocks(plen, max_new),
        }
        self._maybe_finish(slot)
        if self._draft_prefill is not None and slot in self._slots:
            # The external drafter's dense cache never transferred: it
            # re-prefills the full prompt locally (cheap vs the target).
            self._run_draft_prefill([(slot, prompt)])
        self._dirty = True
        self.handoff_imports += 1
        return rid

    # ------------------------------------------------------------ paged kv
    def kv_block_stats(self) -> Dict[str, float]:
        """Arena occupancy: live blocks used/total, LRU-cached and
        refcount-shared prefix blocks, live tokens, and the
        fragmentation ratio (reserved-but-unwritten fraction of used
        blocks). Dense engines report zeros."""
        if not self.paged:
            return {"used": 0, "total": 0, "cached": 0, "shared": 0,
                    "live_tokens": 0, "frag_ratio": 0.0}
        cached = self._prefix.cached_count if self._prefix is not None \
            else 0
        shared = self._prefix.shared_count if self._prefix is not None \
            else 0
        # Parked (cached) blocks are still on the allocator's books —
        # they hold revivable prefix K/V — but they are not LIVE demand.
        used = self.allocator.used_count - cached
        live = sum(st["pos"] for st in self._slots.values())
        cap = used * self.block_size
        # Prefix sharing lets per-slot live tokens exceed the distinct
        # block capacity (two slots counting one shared prefix), so the
        # fragmentation ratio clamps at 0.
        return {"used": used, "total": self.num_blocks - 1,
                "cached": cached, "shared": shared,
                "live_tokens": live,
                "frag_ratio": max(1.0 - live / cap, 0.0) if cap else 0.0}

    def tick_bytes_estimate(self, spec_k: Optional[int] = None) -> int:
        """HBM bytes one decode tick actually streams: the full parameter
        set plus the LIVE tokens' arena traffic (paged) or every slot's
        padded stripe (dense). This is the live-traffic figure the
        achieved-bandwidth gauges and bench_serve report — the compiled
        program's static cost analysis can only ever price the worst
        case.

        ``spec_k`` prices a SPECULATIVE tick (defaults to the k the
        engine currently dispatches): each of the k draft passes streams
        the truncated layer slice (or the external drafter's params +
        cache) plus its share of the live arena, and the batched verify
        streams the full params ONCE plus k+1 per-position arena passes
        — live bytes actually read, so multi-token ticks don't inflate
        the achieved-bandwidth gauges."""
        if spec_k is None:
            spec_k = self._spec_cur_k if self.spec_k else 0
        if self.paged:
            # The kernel streams WHOLE blocks (the run guard skips
            # compute, not the fetch), so round each slot's live prefix
            # up to block granularity — otherwise the figure would be
            # block-size-invariant and the block_size sweep meaningless.
            bs = self.block_size
            live = sum(-(-(st["pos"] + 1) // bs) * bs
                       for st in self._slots.values())
            live_bytes = live * self.cache.token_bytes()
            total = self.param_bytes + live_bytes
            if spec_k:
                if self._draft_cache is not None:
                    dcfg = self.drafter.config
                    ditem = jnp.dtype(self._draft_cache.k.dtype).itemsize
                    dstripes = (2 * dcfg.num_layers * self.num_slots
                                * self.max_len * dcfg.num_kv_heads
                                * dcfg.head_dim * ditem)
                    draft_pass = self._draft_param_bytes + dstripes
                else:
                    frac = self.spec_draft_layers / self.config.num_layers
                    draft_pass = (self._layer_param_bytes * frac
                                  + self._head_param_bytes
                                  + live_bytes * frac)
                # k draft passes + k EXTRA verify query positions (the
                # base figure already counts one arena pass).
                total += spec_k * (draft_pass + live_bytes)
            return total
        c = self.config
        itemsize = jnp.dtype(self.cache.k.dtype).itemsize
        per_slot = (2 * c.num_layers * self.max_len * c.num_kv_heads
                    * c.head_dim * itemsize)
        return self.param_bytes + self.num_slots * per_slot

    def _blocks_needed(self, prompt_len: int, max_new: int) -> int:
        # Spec decode needs spec_k look-ahead tokens past the committed
        # length: rejected draft/verify writes must land inside the
        # slot's own reservation, never a neighbor's block — reserved at
        # admission, all-or-nothing, so free counts stay honest.
        return -(-(prompt_len + max_new + self.spec_k)
                 // self.block_size)

    def _lookahead_blocks(self, prompt_len: int, max_new: int) -> int:
        """Blocks of a reservation attributable to spec look-ahead."""
        return (self._blocks_needed(prompt_len, max_new)
                - -(-(prompt_len + max_new) // self.block_size))

    def _can_admit_head(self) -> bool:
        """True when the FIFO head could admit RIGHT NOW (free slot and,
        when paged, enough free arena blocks — counting LRU-cached
        blocks the allocator can reclaim and prefix blocks a radix
        match would cover). The buffered engine uses this to decide
        whether forcing a sync boundary is worth it — an arena-blocked
        head must not collapse speculative pipelining to one tick per
        sync while it waits for blocks."""
        if not (self._waiting and self._free):
            return False
        if not self.paged:
            return True
        req = self._waiting[0]
        need = self._blocks_needed(len(req["prompt"]), req["max_new"])
        avail = self.allocator.free_count
        if self._prefix is not None:
            nodes = self._prefix.match_nodes(
                self._req_chunks(req)[:self._match_cap(req)])
            m = _bucket_floor(len(nodes))   # admission buckets the same
            need -= m
            # A parked matched block must not count twice: the match
            # will revive it from the LRU (covering part of ``need``)
            # WITHOUT freeing anything, so it is no longer evictable
            # for the novel blocks — an optimistic probe here makes the
            # buffered engine force sync boundaries for an admission
            # that then fails, exactly the pipelining collapse this
            # probe exists to avoid.
            parked = sum(1 for nd in nodes[:m] if nd.refs == 0)
            avail += self._prefix.cached_count - parked
        return need <= avail

    def _match_cap(self, req: Dict[str, Any]) -> int:
        """Blocks a prefix MATCH may cover: full prompt blocks, capped
        so at least one prompt token remains to prefill (the first
        generated token samples from the last prompt position's logits,
        which the KV cache does not store)."""
        return (len(req["prompt"]) - 1) // self.block_size

    def _req_chunks(self, req: Dict[str, Any]) -> List[tuple]:
        """Block-aligned chunk keys for a queued request, memoized on
        the request: the buffered engine's per-tick admission probe and
        the eventual admission itself would otherwise re-tuple the
        whole prompt each time a request waits on the arena."""
        chunks = req.get("chunks")
        if chunks is None:
            chunks = req["chunks"] = prompt_chunks(req["prompt"],
                                                   self.block_size)
        return chunks

    def _alloc_blocks(self, n: int) -> Optional[List[int]]:
        """All-or-nothing reservation with LRU reclaim: when the free
        list can't cover ``n``, refcount-0 cached prefix blocks are
        evicted (leaf-first, oldest-first) before the request is left
        blocking on the arena — cached state never wins over
        admission. Live (refcounted) shared blocks are untouchable."""
        if self._prefix is not None and n > self.allocator.free_count:
            evicted = self._prefix.evict(n - self.allocator.free_count)
            if evicted:
                self.allocator.free(evicted)
        return self.allocator.alloc(n)

    def _table_row(self, blocks: List[int]) -> List[int]:
        # Dead tail entries REPEAT the last live block: pallas skips the
        # re-fetch when consecutive grid steps map to the same block, so
        # a slot's unreached tail costs ~zero HBM traffic. (Entries past
        # a slot's position are masked regardless.)
        tail = blocks[-1] if blocks else GARBAGE_BLOCK
        return blocks + [tail] * (self.max_blocks - len(blocks))

    def _admit(self) -> None:
        if self._import_reservations:
            # Stale import tickets (handoff never arrived) must not
            # starve local admission out of the same arena.
            self.sweep_reservations()
        if not (self._waiting and self._free):
            return
        from ray_tpu._private import metrics_defs as mdefs

        # Drain every admissible request FIRST, grouped by (pow-2 suffix
        # bucket, matched-prefix blocks) — compile reuse, never beyond
        # the cache length — so an admission burst costs one prefill
        # dispatch per group instead of one per request. Slots are
        # independent, so batched admission is bit-identical to the old
        # one-at-a-time loop. Paged engines reserve each request's NOVEL
        # blocks all-or-nothing (FIFO: when the head of the queue
        # doesn't fit the arena even after LRU reclaim, admission
        # stops); matched prefix blocks are pinned read-only instead of
        # allocated, so prefill cost and arena demand both scale with
        # novel tokens.
        bs = self.block_size
        padded_cap = (self.max_blocks * bs if self.paged else self.max_len)
        groups: Dict[tuple, List] = {}
        draft_pending: List = []   # (slot, prompt) for the ext. drafter
        while self._waiting and self._free:
            req = self._waiting[0]
            blocks: List[int] = []
            matched: List[Any] = []
            chunks: List[tuple] = []
            m = 0
            suffix = req["prompt"]
            meta = self._req_meta.get(req["rid"])
            if self.paged:
                if self._prefix is not None:
                    chunks = self._req_chunks(req)
                    matched = self._prefix.match(
                        chunks[:self._match_cap(req)])
                    # Bucket the match DOWN to a power of two so the
                    # compiled prefill program count stays log-bounded
                    # in m (see _bucket_floor); the released tail
                    # parks young in the LRU, still resident for the
                    # next matcher and evictable by _alloc_blocks.
                    m = _bucket_floor(len(matched))
                    if m < len(matched):
                        self._prefix.release(matched[m:])
                        matched = matched[:m]
                need = self._blocks_needed(len(req["prompt"]),
                                           req["max_new"]) - m
                got = self._alloc_blocks(need)
                if got is None:
                    # Head blocked on arena space with a slot free: from
                    # here until admission the wait is ARENA wait, not
                    # queue wait — the TTFT decomposition splits there.
                    if matched:
                        self._prefix.release(matched)
                    if meta is not None and "arena_blocked" not in meta:
                        meta["arena_blocked"] = time.time()
                    break
                blocks = [nd.block for nd in matched] + got
                suffix = req["prompt"][m * bs:]
                padded_len = min(_bucket(len(suffix)),
                                 padded_cap - m * bs)
                padded_len = max(padded_len, bs)  # at least one block
                if self._prefix is not None:
                    self.prefix_hit_tokens += m * bs
                    self.prefix_miss_tokens += len(suffix)
                    if m:
                        self.prefix_hit_requests += 1
                        mdefs.CB_PREFIX_HIT_TOKENS.inc(m * bs,
                                                       tags=self._mtags)
                    mdefs.CB_PREFIX_MISS_TOKENS.inc(len(suffix),
                                                    tags=self._mtags)
            else:
                padded_len = min(_bucket(len(req["prompt"])), padded_cap)
            self._waiting.popleft()
            if meta is not None:
                meta["admit"] = time.time()
                meta["blocks"] = len(blocks)
                meta["prefix_tokens"] = m * bs
            slot = self._free.pop()
            if self.paged:
                self._slot_blocks[slot] = blocks
            groups.setdefault((padded_len, m), []).append(
                (req, slot, blocks, matched, suffix, chunks))
        for (padded_len, m), group in groups.items():
            n = len(group)
            # The batch dim buckets to a power of two as well, so the
            # compiled prefill program count stays log(N) x log(L).
            # Padding rows REPEAT the last request: a duplicate slot
            # index in the scatter writes byte-identical KV twice, which
            # is well-defined; the duplicate's first token is dropped.
            # (Duplicated prefix gathers are reads — trivially safe.)
            n_pad = min(_bucket(n, floor=1), self.num_slots)
            tokens = np.zeros((n_pad, padded_len), np.int32)
            slots = np.zeros(n_pad, np.int32)
            last_idx = np.zeros(n_pad, np.int32)
            npb_w = padded_len // bs if self.paged else 0
            tables_w = np.full((n_pad, npb_w), GARBAGE_BLOCK, np.int32)
            ptables = np.full((n_pad, m), GARBAGE_BLOCK, np.int32)
            for i in range(n_pad):
                req, slot, blocks, matched, suffix, chunks = \
                    group[min(i, n - 1)]
                tokens[i, :len(suffix)] = suffix
                slots[i] = slot
                last_idx[i] = len(suffix) - 1
                if self.paged:
                    # Suffix K/V land in the slot's NEW blocks (the
                    # matched prefix is read-only); bucket-padding
                    # overflow past the reservation writes masked
                    # garbage to block 0.
                    new_blocks = blocks[m:]
                    k = min(len(new_blocks), npb_w)
                    tables_w[i, :k] = new_blocks[:k]
                    ptables[i, :m] = blocks[:m]
            t0 = time.perf_counter()
            pt0 = time.time()  # wall-clock anchor for the prefill span
            pstep = jnp.int32(self._prefill_count)
            self._prefill_count += 1
            if self.paged:
                first, self.cache = self._prefill(
                    self.params, jnp.asarray(tokens), self.cache,
                    jnp.asarray(ptables), jnp.asarray(tables_w),
                    jnp.asarray(last_idx), pstep)
            else:
                first, self.cache = self._prefill(
                    self.params, jnp.asarray(tokens), self.cache,
                    jnp.asarray(slots), jnp.asarray(last_idx), pstep)
            first = np.asarray(first)            # N ints, one transfer
            # The fetch syncs the dispatch, so this interval is the real
            # prefill cost — bench_serve derives prefill tokens/s from
            # it without decode/queueing time polluting the denominator,
            # and the XLA monitor turns it into achieved-FLOPs/bandwidth
            # gauges against this bucket's compiler cost analysis.
            prefill_wall = time.perf_counter() - t0
            self.prefill_seconds += prefill_wall
            self._prefill.note_execution(prefill_wall)
            self._prefill_shapes.add((n_pad, padded_len))
            true_tokens = int(last_idx[:n].sum()) + n
            self.prefill_batches += 1
            self.prefill_requests += n
            self.prefill_tokens += true_tokens
            mdefs.CB_PREFILL_REQUESTS.inc(n, tags=self._mtags)
            mdefs.CB_PREFILL_TOKENS.inc(true_tokens, tags=self._mtags)
            first_ts = time.time()  # the fetch above synced the device
            for (req, slot, blocks, matched, _sfx, chunks), tok in \
                    zip(group, first):
                tok = int(tok)
                meta = self._req_meta.get(req["rid"])
                if meta is not None:
                    self._note_first_token(meta, pt0, first_ts)
                if self._prefix is not None and chunks:
                    # Index this prompt's full blocks now that the
                    # dispatch above ordered their arena writes (the
                    # donated-cache dependency chain sequences any later
                    # prefill's gather after them). A chunk already
                    # indexed under another block — a cold twin admitted
                    # this same round — stops the walk and leaves the
                    # remaining blocks exclusive.
                    created = self._prefix.insert(chunks, blocks,
                                                  start=len(matched))
                    if matched or created:
                        self._slot_nodes[slot] = matched + created
                if self.token_callback is not None:
                    self.token_callback(req["rid"], tok)
                self._slots[slot] = {
                    "rid": req["rid"], "out": [tok],
                    "max_new": req["max_new"],
                    "pos": len(req["prompt"]),   # next decode writes here
                    "last": tok,
                    # Reserved-but-speculative block head-room, reported
                    # by pressure_snapshot (router congestion must see
                    # it as occupied, not free).
                    "la_blocks": (self._lookahead_blocks(
                        len(req["prompt"]), req["max_new"])
                        if self.paged else 0),
                }
                self._maybe_finish(slot)
                if self.role == "prefill":
                    # Prefill-role engines stop at the first token: park
                    # the slot's blocks for export instead of entering
                    # the decode tick (a request _maybe_finish already
                    # completed — max_new=1 / immediate EOS — has
                    # nothing to hand off and stays finished).
                    self._park_for_handoff(slot, req)
                if (self._draft_prefill is not None
                        and slot in self._slots):
                    draft_pending.append((slot, req["prompt"]))
        if self._draft_prefill is not None and draft_pending:
            self._run_draft_prefill(draft_pending)
        self._dirty = True  # device tokens/positions need re-upload

    def _run_draft_prefill(self, admitted) -> None:
        """Prefill the external drafter's dense cache for freshly
        admitted slots — FULL prompts (the target's prefix cache only
        shortens the target's prefill), grouped into the same pow-2
        buckets as the main prefill so the program count stays
        log-bounded. Padding garbage past each prompt is dead: the
        drafter's first decode write at position p overwrites before
        position p is ever attended."""
        by_bucket: Dict[int, List] = {}
        for slot, prompt in admitted:
            blen = min(_bucket(len(prompt)), self.max_len)
            by_bucket.setdefault(blen, []).append((slot, prompt))
        for blen, grp in by_bucket.items():
            n = len(grp)
            n_pad = min(_bucket(n, floor=1), self.num_slots)
            toks = np.zeros((n_pad, blen), np.int32)
            slots_arr = np.zeros(n_pad, np.int32)
            for i in range(n_pad):
                slot, prompt = grp[min(i, n - 1)]
                toks[i, :len(prompt)] = prompt
                slots_arr[i] = slot
            self._draft_cache = self._draft_prefill(
                self.drafter.params, jnp.asarray(toks),
                self._draft_cache, jnp.asarray(slots_arr))

    def _maybe_finish(self, slot: int) -> None:
        st = self._slots.get(slot)
        if st is None:
            return
        done = len(st["out"]) >= st["max_new"] or (
            self.eos_token is not None and st["out"][-1] == self.eos_token)
        if done:
            self._finished[st["rid"]] = st["out"]
            del self._slots[slot]
            self._release_slot(slot)
            self._finish_request(st["rid"], "finished",
                                 tokens=len(st["out"]))

    def _upload_state(self) -> None:
        tokens = np.zeros(self.num_slots, np.int32)
        positions = np.zeros(self.num_slots, np.int32)
        for slot, st in self._slots.items():
            tokens[slot] = st["last"]
            positions[slot] = st["pos"]
        self._d_tokens = jnp.asarray(tokens)
        self._d_positions = jnp.asarray(positions)
        # The device sampling-step counter rewinds to the host-applied
        # count: speculative ticks a rewind discarded replay the SAME
        # step numbers, so sampled decode reproduces exactly like greedy.
        self._d_step = jnp.int32(self._applied_steps)
        if self.paged:
            tables = np.zeros((self.num_slots, self.max_blocks), np.int32)
            limits = np.zeros(self.num_slots, np.int32)
            for slot, blocks in self._slot_blocks.items():
                tables[slot] = self._table_row(blocks)
                limits[slot] = len(blocks) * self.block_size
            self._d_tables = jnp.asarray(tables)
            self._d_limits = jnp.asarray(limits)
        self._dirty = False

    def _run_tick(self):
        """Dispatch one decode tick. Returns the device row to fetch:
        a [B] token vector from the plain tick, or a
        ``(committed [B, k+1], counts [B])`` pair from a spec tick. At
        k = 0 — spec off, or the accept-rate controller parked at the
        bottom rung — this dispatches the EXACT pre-spec ``cb_tick``
        program: same jit, same arguments, same device sequence."""
        k = self._spec_cur_k if (self.spec_k and self.paged) else 0
        if k > 0:
            tick = self._get_spec_tick(k)
            if self._draft_cache is not None:
                (committed, counts, self._d_tokens, self._d_positions,
                 self.cache, self._draft_cache, self._d_step) = tick(
                    self.params, self._d_tokens, self._d_positions,
                    self._d_tables, self._d_limits, self.cache,
                    self._draft_cache, self._d_step, self.drafter.params)
            else:
                (committed, counts, self._d_tokens, self._d_positions,
                 self.cache, _, self._d_step) = tick(
                    self.params, self._d_tokens, self._d_positions,
                    self._d_tables, self._d_limits, self.cache,
                    self._d_step)
            self.spec_tick_count += 1
            self._last_tick_k = k
            return (committed, counts)
        if self.paged:
            (self._d_tokens, self._d_positions, self.cache,
             self._d_step) = self._tick(
                self.params, self._d_tokens, self._d_positions,
                self._d_tables, self._d_limits, self.cache, self._d_step)
        else:
            (self._d_tokens, self._d_positions, self.cache,
             self._d_step) = self._tick(
                self.params, self._d_tokens, self._d_positions,
                self.cache, self._d_step)
        self.base_tick_count += 1
        self._last_tick_k = 0
        return self._d_tokens

    def _record_window_token(self, rid: int, entries: Dict[int, list],
                             w0: float, w1: float) -> None:
        """Attribute one applied token to the current sync window of a
        TRACED request (span emission is deferred to finish). Past the
        per-request window cap the tail merges into one aggregate so a
        long generation can't flood the span buffer."""
        ent = entries.get(rid)
        if ent is not None:
            ent[2] += 1
            return
        rec = self._req_meta.get(rid)
        if rec is None or not rec["traced"]:
            return
        wins = rec["windows"]
        if len(wins) < self._MAX_WINDOWS:
            ent = [w0, w1, 1]
            wins.append(ent)
        else:
            ent = rec.get("window_tail")
            if ent is None:
                ent = rec["window_tail"] = [w0, w1, 0, 0]
            ent[1] = w1
            ent[3] += 1
            ent[2] += 1
            entries[rid] = ent
            return
        entries[rid] = ent

    def _apply_tokens(self, nxt_rows, membership, window=None) -> bool:
        """Book one or more fetched tick rows; returns True when any
        request finished (membership changed). ``window`` is the
        (wall_start, wall_end) of the sync window these rows cover —
        recorded per traced request for the decode-window spans (windows
        must attach BEFORE ``_maybe_finish`` pops the record, so this
        rides the apply loop, not a post-pass)."""
        finished_any = False
        applied = 0
        drafted = 0
        accepted = 0
        track = window is not None and self._traced_live > 0
        if track:
            w1 = window[1]
            w0 = window[0] if window[0] is not None else w1
            entries: Dict[int, list] = {}
        # One device tick == one sampling step regardless of how many
        # tokens it committed (spec windows burn exactly one step number),
        # so the rewind counter advances per ROW, not per token.
        self._applied_steps += len(nxt_rows)
        for row in nxt_rows:
            if isinstance(row, tuple):
                toks, counts = row   # spec tick: ([B, k+1], [B]) committed
            else:
                toks, counts = row, None
            for slot, rid in membership:
                st = self._slots.get(slot)
                if st is None or st["rid"] != rid:
                    continue  # finished earlier in this batch: skip tail
                n = 1 if counts is None else int(counts[slot])
                if counts is not None:
                    drafted += toks.shape[1] - 1
                    accepted += n - 1
                for j in range(n):
                    tok = int(toks[slot]) if counts is None else int(
                        toks[slot, j])
                    if self.token_callback is not None:
                        self.token_callback(rid, tok)
                    st["out"].append(tok)
                    st["last"] = tok
                    st["pos"] += 1
                    applied += 1
                    if track:
                        self._record_window_token(rid, entries, w0, w1)
                    self._maybe_finish(slot)
                    if slot not in self._slots:
                        # EOS / max_new mid-window: the rest of the
                        # committed window is past the request's end —
                        # drop it (device-side overrun rewinds with the
                        # dirty re-upload the finish already forces).
                        finished_any = True
                        break
        self.decoded_tokens += applied
        if applied or drafted:
            from ray_tpu._private import metrics_defs as mdefs

            if applied:
                mdefs.CB_DECODE_TOKENS.inc(applied, tags=self._mtags)
            if drafted:
                self.spec_draft_tokens += drafted
                self.spec_accepted_tokens += accepted
                self._spec_window.append((drafted, accepted))
                mdefs.CB_SPEC_DRAFT_TOKENS.inc(drafted, tags=self._mtags)
                mdefs.CB_SPEC_ACCEPTED_TOKENS.inc(accepted,
                                                  tags=self._mtags)
        return finished_any

    # Accept-rate controller thresholds: shrink k below LOW (drafts are
    # wasting verify bandwidth), grow above HIGH (more look-ahead pays),
    # hold in between. MIN_SAMPLE drafted tokens gate any move so one
    # unlucky window can't thrash the rung.
    _SPEC_RATE_LOW = 0.3
    _SPEC_RATE_HIGH = 0.6
    _SPEC_MIN_SAMPLE = 16

    @property
    def spec_accept_rate(self) -> float:
        """Windowed draft accept rate: accepted / drafted over the last
        ``RAY_TPU_SPEC_WINDOW`` spec rows (0.0 when no drafts yet)."""
        drafted = sum(d for d, _ in self._spec_window)
        if not drafted:
            return 0.0
        return sum(a for _, a in self._spec_window) / drafted

    def _adapt_spec_k(self) -> None:
        """Move the live draft depth along the rung ladder from the
        windowed accept rate. Called ONLY at clean boundaries (sync path
        per step; buffered path when no ticks are in flight), so a rung
        change never mixes row widths inside one stacked fetch. At rung 0
        the engine runs the exact pre-spec tick program; a probe
        re-enters the bottom rung after ``RAY_TPU_SPEC_PROBE_TICKS``
        base ticks so a workload whose accept rate recovers isn't parked
        at 0 forever."""
        if not (self.spec_k and self.spec_adaptive):
            return
        if self._spec_cur_k == 0:
            self._spec_probe_countdown -= 1
            if self._spec_probe_countdown <= 0:
                self._spec_cur_k = self._spec_ladder_ks[0]
                self._spec_window.clear()
                self._spec_probe_countdown = self._spec_probe_after
            return
        drafted = sum(d for d, _ in self._spec_window)
        if drafted < self._SPEC_MIN_SAMPLE:
            return
        rate = self.spec_accept_rate
        idx = self._spec_ladder_ks.index(self._spec_cur_k)
        if rate < self._SPEC_RATE_LOW:
            self._spec_cur_k = (
                self._spec_ladder_ks[idx - 1] if idx > 0 else 0)
            self._spec_window.clear()
            self._spec_probe_countdown = self._spec_probe_after
        elif rate > self._SPEC_RATE_HIGH and (
                idx + 1 < len(self._spec_ladder_ks)):
            self._spec_cur_k = self._spec_ladder_ks[idx + 1]
            self._spec_window.clear()

    def _emit_gauges(self) -> None:
        from ray_tpu._private import metrics_defs as mdefs

        active = len(self._slots)
        mdefs.CB_ACTIVE_SLOTS.set(active, tags=self._mtags)
        mdefs.CB_WAITING_REQUESTS.set(len(self._waiting), tags=self._mtags)
        mdefs.CB_SLOT_OCCUPANCY.set(active / max(self.num_slots, 1),
                                    tags=self._mtags)
        if self.paged:
            kv = self.kv_block_stats()
            mdefs.CB_KV_BLOCKS_USED.set(kv["used"], tags=self._mtags)
            mdefs.CB_KV_BLOCKS_TOTAL.set(kv["total"], tags=self._mtags)
            mdefs.CB_KV_FRAG_RATIO.set(kv["frag_ratio"], tags=self._mtags)
            if self._prefix is not None:
                mdefs.CB_KV_BLOCKS_CACHED.set(kv["cached"],
                                              tags=self._mtags)
                mdefs.CB_KV_BLOCKS_SHARED.set(kv["shared"],
                                              tags=self._mtags)
        if self.spec_k:
            mdefs.CB_SPEC_ACCEPT_RATE.set(self.spec_accept_rate,
                                          tags=self._mtags)
            mdefs.CB_SPEC_K.set(self._spec_cur_k, tags=self._mtags)

    def step(self) -> Dict[int, List[int]]:
        """Admit waiting requests, run one decode tick over all active
        slots, and return the requests that finished (with
        ``sync_every > 1``, finish detection lags up to 2K ticks)."""
        from ray_tpu._private import chaos
        from ray_tpu._private import metrics_defs as mdefs

        if chaos.enabled():
            # Delayed-engine-tick chaos site (``delay_tick``): decode
            # stutters — a slow device, a co-tenant hog — with every
            # request still alive. Drains under load and streaming
            # timeouts must ride it out.
            chaos.inject("serve_tick", engine=self._mtags["engine"])
        self._emit_gauges()
        if self.sync_every == 1:
            self._adapt_spec_k()
            self._admit()
            if self._slots:
                if self._dirty:
                    self._upload_state()
                w0 = time.time() if self._traced_live else None
                t0 = time.perf_counter()
                nxt_dev = self._run_tick()
                if isinstance(nxt_dev, tuple):
                    nxt = (np.asarray(nxt_dev[0]), np.asarray(nxt_dev[1]))
                else:
                    nxt = np.asarray(nxt_dev)  # 4 bytes/slot
                # Per-tick sync: the fetch IS the device sync, so this is
                # the honest tick latency (dispatch + compute + fetch) —
                # also the denominator for the tick's achieved-FLOPs/
                # bandwidth gauges. The bytes hint keeps achieved
                # bandwidth priced off LIVE tokens, not the compiled
                # worst case.
                tick_wall = time.perf_counter() - t0
                mdefs.CB_TICK_MS.observe(tick_wall * 1e3, tags=self._mtags)
                # Paged ticks get the live-byte hint (the compiled cost
                # prices every table entry as live); the dense program's
                # own cost analysis is already accurate — including the
                # kernel-off fp32 re-read traffic a hand estimate would
                # miss — so dense keeps it. Spec ticks report against
                # THEIR program (per-k instrumented jit) with the hint
                # priced for k draft passes + the wider verify window.
                tick_fn = (self._spec_ticks[self._last_tick_k]
                           if self._last_tick_k else self._tick)
                tick_fn.note_execution(
                    tick_wall,
                    bytes_hint=(self.tick_bytes_estimate(
                        spec_k=self._last_tick_k)
                                if self.paged else None))
                if self._apply_tokens(
                        [nxt], [(s, st["rid"])
                                for s, st in self._slots.items()],
                        window=(w0, time.time())
                        if w0 is not None else None):
                    self._dirty = True
            out, self._finished = self._finished, {}
            return out
        return self._step_buffered()

    def _step_buffered(self) -> Dict[int, List[int]]:
        # Admission only at a clean boundary (no speculative ticks in
        # flight): an upload mid-buffer would rewind the device sequence.
        if not self._buf and self._pending is None:
            # Spec-k changes only ever land here (clean boundary): a
            # mid-buffer rung switch would mix row widths in one stacked
            # fetch and desync the replayed device sequence on rewind.
            self._adapt_spec_k()
            self._admit()
            # Clean boundary: restart the bandwidth window so idle gaps
            # and admission prefill time never pollute the first
            # buffered window's per-tick denominator (the achieved-BW
            # gauges would otherwise report near-zero bandwidth after
            # an idle period).
            self._bw_window_t0 = None
            self._bw_window_ticks = 0
        if self._slots:
            if self._dirty and not self._buf and self._pending is None:
                self._upload_state()
            from ray_tpu._private import metrics_defs as mdefs

            if not self._buf and self._traced_live:
                # A fresh speculative buffer starts: its ticks form ONE
                # sync window for the decode-window spans (the host only
                # observes tokens at the next fetch, so finer-grained
                # timing would be fiction).
                self._window_t0 = time.time()
            if self._bw_window_t0 is None:
                self._bw_window_t0 = time.perf_counter()
            t0 = time.perf_counter()
            nxt_dev = self._run_tick()
            # Buffered mode overlaps fetches with compute, so this is
            # dispatch time only; steady-state backpressure still makes
            # the histogram track the real tick cadence.
            mdefs.CB_TICK_MS.observe(
                (time.perf_counter() - t0) * 1e3, tags=self._mtags)
            self._bw_window_ticks += 1
            if not self._buf:
                # k is frozen for the whole buffered window (adaptation
                # happens at clean boundaries only) — remember which
                # program produced these rows for the flush accounting.
                self._window_k = self._last_tick_k
            self._buf.append(nxt_dev)
        want_admit = self._can_admit_head()
        if len(self._buf) >= self.sync_every or want_admit or (
                not self._slots and (self._buf or self._pending is not None)):
            # Non-K arms drain in-flight state early: a waiting request
            # with a free slot must not starve behind steady pipelining
            # (time-to-first-token), and a cancel of the last request
            # must not wedge admission.
            self._flush_buffered(force_boundary=want_admit)
        out, self._finished = self._finished, {}
        return out

    @staticmethod
    def _stack_buffer(buf):
        """Stack buffered tick rows into one fetchable device value.
        Plain rows ([B] vectors) stack to [T, B]; spec rows stack
        componentwise to ([T, B, k+1], [T, B]) — k is constant across a
        window, so the stack is uniform."""
        if isinstance(buf[0], tuple):
            return (jnp.stack([r[0] for r in buf]),
                    jnp.stack([r[1] for r in buf]))
        return jnp.stack(buf)

    @staticmethod
    def _rows_from_stacked(stacked):
        """Fetch a stacked buffer to host and split it back into per-tick
        rows for ``_apply_tokens`` (spec rows become (toks, counts)
        pairs)."""
        if isinstance(stacked, tuple):
            toks = np.asarray(stacked[0])
            counts = np.asarray(stacked[1])
            return [(toks[i], counts[i]) for i in range(toks.shape[0])]
        rows = np.asarray(stacked)
        return list(rows)

    def _flush_buffered(self, force_boundary: bool = False) -> None:
        # 1. Apply the PRIOR pending fetch first — its transfer has been
        # overlapping the ticks just buffered. If it finished requests,
        # the current buffer is stale speculation over freed slots:
        # discard it and rewind (re-upload host state next step).
        if self._pending is not None:
            stacked, membership, win0, wk = self._pending
            self._pending = None
            rows = self._rows_from_stacked(stacked)  # overlapped fetch
            # The fetch landing IS a device sync: backpressure makes the
            # wall time since the last sync cover the ticks dispatched in
            # between, so window/ticks is the steady-state per-tick cost.
            # Feed it (with the live-byte hint) to the achieved-bandwidth
            # gauges — buffered mode is the production remote-chip path,
            # and without this the gauges would price the paged tick at
            # the compiled worst case instead of live tokens. Spec
            # windows report against their per-k program with the hint
            # priced for the drafts + wider verify those ticks ran.
            now = time.perf_counter()
            if self._bw_window_t0 is not None and self._bw_window_ticks:
                tick_fn = self._spec_ticks[wk] if wk else self._tick
                tick_fn.note_execution(
                    (now - self._bw_window_t0) / self._bw_window_ticks,
                    bytes_hint=(self.tick_bytes_estimate(spec_k=wk)
                                if self.paged else None))
            self._bw_window_t0 = now
            self._bw_window_ticks = 0
            if self._apply_tokens(rows, membership,
                                  window=(win0, time.time())):
                self._buf = []
                self._dirty = True
                return
        if force_boundary and self._buf:
            # A waiting request needs a clean boundary to admit: apply the
            # just-stacked-would-be buffer SYNCHRONOUSLY instead of
            # pipelining it, then rewind so the next step re-admits.
            rows = self._rows_from_stacked(self._stack_buffer(self._buf))
            membership = [(s, st["rid"]) for s, st in self._slots.items()]
            self._buf = []
            win0, self._window_t0 = self._window_t0, None
            self._apply_tokens(rows, membership,
                               window=(win0, time.time()))
            self._dirty = True
            return
        if not self._buf:
            return
        # 2. Stack this buffer into ONE transfer and start it async; it
        # lands while the next K ticks run.
        stacked = self._stack_buffer(self._buf)
        self._buf = []
        for part in (stacked if isinstance(stacked, tuple) else (stacked,)):
            try:
                part.copy_to_host_async()
            except Exception:  # noqa: BLE001 — platform without async copy
                pass
        self._pending = (stacked,
                         [(s, st["rid"])
                          for s, st in self._slots.items()],
                         self._window_t0, self._window_k)
        self._window_t0 = None

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drive ticks until every submitted request finished."""
        results: Dict[int, List[int]] = {}
        while self.has_work():
            results.update(self.step())
        return results
