"""Continuous batching: iteration-level scheduling for LLM serving.

Reference: the vLLM-style engine behind ``ray.serve.llm``
(``python/ray/llm/_internal/serve``) — instead of batching whole
requests (head-of-line blocking on the longest generation), the engine
owns a fixed pool of KV-cache slots; requests prefill into a free slot
and join the very next decode tick, and finished requests free their
slot immediately for queued work.

TPU-native shape discipline: the decode tick is ONE jitted program over
all ``num_slots`` slots (static shapes; inactive slots compute masked
garbage), per-slot absolute positions drive RoPE/cache scatter/causal
masking, and prompt prefills pad to power-of-two buckets so the number
of compiled programs stays logarithmic. Padded prefill is sound without
length masking because a slot's garbage cache entries live only at
positions strictly greater than its next decode position — every decode
overwrites position ``p`` before attending ``[0..p]``.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private import xla_monitor
from ray_tpu.models import llama
from ray_tpu.models.inference import KVCache, _forward_cached, lm_head_logits
from ray_tpu.models.llama import rms_norm
from ray_tpu.ops.decode_attention import (decode_applicable,
                                          decode_attention,
                                          decode_attention_reference,
                                          env_flag)
from ray_tpu.ops.rope import rope_frequencies


def _apply_rope_batched(x, cos, sin):
    """RoPE with per-batch angles: x [B, 1, H, D], cos/sin [B, D//2]."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, None, None, :]
    s = sin[:, None, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(dtype)


def _scatter_slot(cache, new, positions):
    """cache [B, S_max, KVH, D]; new [B, KVH, D] written at per-slot
    ``positions`` [B]."""
    def one(c, n, p):
        return jax.lax.dynamic_update_slice(c, n[None], (p, 0, 0))

    return jax.vmap(one)(cache, new, positions)


# The XLA reference single-query attention now lives next to the fused
# kernel (ops/decode_attention.py); keep the old name importable — it is
# the parity baseline the kernel tests compare against.
_attend_decode = decode_attention_reference


def _decode_tick(params, tokens, positions, cache: KVCache,
                 config: llama.LlamaConfig, use_kernel: bool = False):
    """One decode step for every slot: tokens [B] at per-slot absolute
    ``positions`` [B]. Returns (logits [B, V], cache).

    ``use_kernel`` (static) routes attention through the fused pallas
    decode kernel — one pass over the KV pool in its storage dtype —
    instead of the fp32-upcast whole-cache einsums of the reference."""
    c = config
    cos, sin = rope_frequencies(c.head_dim, 0, c.rope_theta,
                                positions=positions)  # [B, D//2]
    x = params["embed"].astype(c.dtype)[tokens][:, None, :]   # [B, 1, E]
    scale = c.head_dim ** -0.5

    def layer_fn(carry, inputs):
        # Cache rides the CARRY (updated in place layer by layer via
        # dynamic_update_slice), not scan xs/ys: threading it as
        # per-iteration inputs/outputs made XLA materialize full cache
        # copies every tick — the decode tick was 2-3x the HBM roofline
        # from copy traffic alone.
        x, ck_all, cv_all, li = carry
        layer = inputs
        ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
        h = rms_norm(x, layer["attn_norm"], c.rms_eps)
        q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(c.dtype))
        k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(c.dtype))
        v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(c.dtype))
        q = _apply_rope_batched(q, cos, sin)
        k = _apply_rope_batched(k, cos, sin)
        ck = _scatter_slot(ck, k[:, 0].astype(ck.dtype), positions)
        cv = _scatter_slot(cv, v[:, 0].astype(cv.dtype), positions)
        o = decode_attention(q[:, 0], ck, cv, positions, scale,
                             use_kernel=use_kernel)
        ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
        cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
        x = x + jnp.einsum("bhd,hde->be", o,
                           layer["wo"].astype(c.dtype))[:, None, :]
        h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
        gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(c.dtype))
        up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(c.dtype))
        x = x + jnp.einsum("bsm,me->bse", jax.nn.silu(gate) * up,
                           layer["w_down"].astype(c.dtype))
        return (x, ck_all, cv_all, li + 1), None

    (x, new_k, new_v, _), _ = jax.lax.scan(
        layer_fn, (x, cache.k, cache.v, jnp.int32(0)), params["layers"])
    x = rms_norm(x, params["final_norm"], c.rms_eps)
    # lm_head in the params' storage dtype with fp32 accumulation (shared
    # with the prefill path) — bf16 params are no longer upcast in HBM.
    logits = lm_head_logits(x, params, c)
    # Greedy selection stays ON DEVICE: the host needs 4 bytes per slot,
    # not the [B, V] logits — shipping full logits per tick was the
    # serving bottleneck on remote-attached chips (512KB x RTT per token).
    next_tokens = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
    return next_tokens, positions + 1, KVCache(k=new_k, v=new_v)


def _bucket(n: int, floor: int = 16) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _resolve_decode_kernel(config: llama.LlamaConfig, max_len: int,
                           use_decode_kernel: Optional[bool]) -> bool:
    """Engine-level kernel toggle: explicit arg > RAY_TPU_DECODE_KERNEL
    env > auto (fused kernel on TPU when the shapes tile; the XLA
    reference elsewhere — CPU tests opt in explicitly and run the kernel
    in interpret mode)."""
    from ray_tpu.ops.decode_attention import pltpu as _pltpu

    if _pltpu is None:
        # No pallas TPU support in this jax build: the dispatcher would
        # silently run the reference, so report the truth.
        return False
    if use_decode_kernel is None:
        use_decode_kernel = env_flag("RAY_TPU_DECODE_KERNEL")
    if use_decode_kernel is None:
        return (jax.default_backend() == "tpu"
                and decode_applicable(max_len, config.head_dim,
                                      config.num_heads,
                                      config.num_kv_heads))
    return bool(use_decode_kernel)


class ContinuousBatcher:
    """Iteration-level scheduler over a fixed pool of KV-cache slots."""

    _engine_ids = itertools.count()  # per-process engine tag suffix

    def __init__(self, config: llama.LlamaConfig, params=None,
                 num_slots: int = 8, max_len: int = 512, seed: int = 0,
                 eos_token: Optional[int] = None, token_callback=None,
                 sync_every: int = 1,
                 use_decode_kernel: Optional[bool] = None):
        """``token_callback(rid, token)`` fires for every generated token
        as it is produced (serving streams ride this).

        ``sync_every=K > 1`` enables SPECULATIVE BUFFERED decode for
        high-latency host↔device links (remote-attached chips: a fetch
        costs a full tunnel RTT regardless of size): the engine runs K
        ticks per host synchronization, fetching token batches
        double-buffered so the transfer overlaps the next K ticks'
        compute. Greedy decode is deterministic, so ticks run ahead of
        host bookkeeping speculatively; when a request finishes, the
        engine rewinds to host-known state and redoes ≤2K ticks (freed
        slots need re-admission). Outputs are bit-identical to
        ``sync_every=1``; only finish *detection* lags.

        ``use_decode_kernel`` routes decode attention through the fused
        pallas kernel (``ops/decode_attention.py``); ``None`` resolves
        via ``RAY_TPU_DECODE_KERNEL`` then auto (TPU with tiling shapes).
        Outputs are bit-identical kernel on/off."""
        self.config = config
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token = eos_token
        self.sync_every = max(1, int(sync_every))
        self.use_decode_kernel = _resolve_decode_kernel(
            config, max_len, use_decode_kernel)
        # Prefill accounting (bench_serve.py reads these; the metric
        # counters mirror them into the TSDB).
        self.prefill_batches = 0
        self.prefill_requests = 0
        self.prefill_tokens = 0
        self.prefill_seconds = 0.0          # dispatch->first-token sync
        self._prefill_shapes: set = set()   # (N_pad, L_pad) compiled
        self._buf: List[Any] = []       # unstacked device token vectors
        self._pending: Optional[tuple] = None  # (stacked, [(slot, rid)])
        self.params = params if params is not None else llama.init_params(
            config, jax.random.PRNGKey(seed))
        self.token_callback = token_callback
        self.cache = KVCache.create(config, num_slots, max_len)
        self._free: List[int] = list(range(num_slots))
        self._slots: Dict[int, Dict[str, Any]] = {}   # slot -> request
        # Device-resident decode state: last tokens + positions live on
        # the chip between ticks (uploaded only when slot membership
        # changes), so a steady decode tick moves 4 bytes/slot host-ward
        # and nothing device-ward.
        self._d_tokens = None
        self._d_positions = None
        self._dirty = True
        self._waiting: deque = deque()
        self._rid = itertools.count()
        self._finished: Dict[int, List[int]] = {}
        # Observability: engine label for the slot-occupancy / decode-rate
        # series (continuous-batching is the serving hot loop the decode
        # roofline work tunes — the TSDB needs its history). The instance
        # counter keeps co-resident engines' series from colliding.
        self._mtags = {"engine":
                       f"slots{num_slots}-{next(self._engine_ids)}"}
        cfg = config

        use_kernel = self.use_decode_kernel

        # The XLA monitor dispatches per signature and audits shape
        # growth: prefill's signatures are pow-2 bucketed in N and L by
        # design (allowed caps included — max_len/num_slots need not be
        # powers of two), so legitimate bucket growth stays silent while
        # a stray odd shape raises ray_tpu_xla_retraces_total. The tick
        # has exactly ONE legitimate signature.
        @xla_monitor.instrument(name="cb_prefill", shape_policy="bucketed",
                                allowed_dims=(max_len, num_slots),
                                donate_argnums=(2,))
        def prefill(params, tokens, cache, slots, last_idx):
            # BATCHED BUCKETED PREFILL: tokens [N, L] holds N same-bucket
            # prompts destined for KV slots ``slots`` [N]; ``last_idx``
            # [N] is each prompt's true_len - 1. Slot gather + write-back
            # live INSIDE the jit with the pooled cache donated, so an
            # admission burst is one in-place program, not N whole-cache
            # copies. Only the N first tokens leave the device (argmax on
            # chip), not [N, L, V] logits.
            positions = jnp.arange(tokens.shape[1])
            slot_cache = KVCache(k=jnp.take(cache.k, slots, axis=1),
                                 v=jnp.take(cache.v, slots, axis=1))
            logits, sc = _forward_cached(params, tokens, positions,
                                         slot_cache, cfg)
            cache = KVCache(k=cache.k.at[:, slots].set(sc.k),
                            v=cache.v.at[:, slots].set(sc.v))
            last = jnp.take_along_axis(
                logits, last_idx[:, None, None], axis=1)[:, 0]   # [N, V]
            first = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return first, cache

        @xla_monitor.instrument(name="cb_tick", donate_argnums=(3,))
        def tick(params, tokens, positions, cache):
            return _decode_tick(params, tokens, positions, cache, cfg,
                                use_kernel=use_kernel)

        self._prefill = prefill
        self._tick = tick

    def prefill_cache_misses(self) -> int:
        """Compiled prefill program count (one per (N, bucket) shape) —
        the admission-burst acceptance check reads this. Prefers jax's
        real jit-cache counter (private API); falls back to the shapes
        this engine dispatched if a jax upgrade drops it."""
        cache_size = getattr(self._prefill, "_cache_size", None)
        if cache_size is not None:
            return cache_size()
        return len(self._prefill_shapes)

    # ---------------------------------------------------------------- api
    def submit(self, prompt_tokens: List[int],
               max_new_tokens: int = 32) -> int:
        """Queue a request; returns its id. It joins the next tick with a
        free slot — no waiting for the current batch to drain."""
        assert len(prompt_tokens) + max_new_tokens <= self.max_len
        rid = next(self._rid)
        if max_new_tokens <= 0:
            # Nothing to generate: finish immediately, no slot occupied.
            self._finished[rid] = []
            return rid
        self._waiting.append({"rid": rid,
                              "prompt": list(prompt_tokens),
                              "max_new": max_new_tokens})
        return rid

    def cancel(self, rid: int) -> bool:
        """Drop a request (client disconnected): frees its slot / queue
        spot so abandoned generations stop burning decode ticks."""
        for i, req in enumerate(self._waiting):
            if req["rid"] == rid:
                del self._waiting[i]
                return True
        for slot, st in list(self._slots.items()):
            if st["rid"] == rid:
                del self._slots[slot]
                self._free.append(slot)
                self._dirty = True
                return True
        return self._finished.pop(rid, None) is not None

    def reset(self) -> List[int]:
        """Abort everything (recovery after an engine error). Returns the
        request ids that were dropped."""
        dropped = [st["rid"] for st in self._slots.values()]
        dropped += [r["rid"] for r in self._waiting]
        self._slots.clear()
        self._waiting.clear()
        self._free = list(range(self.num_slots))
        self._finished.clear()
        self._buf = []
        self._pending = None
        # The prefill/tick jits donate the pooled cache; after a mid-step
        # failure the old buffers may already be deleted, so rebuild the
        # pool or every later step would raise "Array has been deleted".
        self.cache = KVCache.create(self.config, self.num_slots,
                                    self.max_len)
        self._dirty = True
        return dropped

    @property
    def active_count(self) -> int:
        return len(self._slots)

    def has_work(self) -> bool:
        return bool(self._slots or self._waiting or self._finished
                    or self._buf or self._pending)

    def _admit(self) -> None:
        if not (self._waiting and self._free):
            return
        from ray_tpu._private import metrics_defs as mdefs

        # Drain every admissible request FIRST, grouped by power-of-two
        # bucket (compile reuse, never beyond the cache length), so an
        # admission burst costs one prefill dispatch per bucket instead
        # of one per request. Slots are independent, so batched admission
        # is bit-identical to the old one-at-a-time loop.
        groups: Dict[int, List] = {}
        while self._waiting and self._free:
            req = self._waiting.popleft()
            slot = self._free.pop()
            padded_len = min(_bucket(len(req["prompt"])), self.max_len)
            groups.setdefault(padded_len, []).append((req, slot))
        for padded_len, group in groups.items():
            n = len(group)
            # The batch dim buckets to a power of two as well, so the
            # compiled prefill program count stays log(N) x log(L).
            # Padding rows REPEAT the last request: a duplicate slot
            # index in the scatter writes byte-identical KV twice, which
            # is well-defined; the duplicate's first token is dropped.
            n_pad = min(_bucket(n, floor=1), self.num_slots)
            tokens = np.zeros((n_pad, padded_len), np.int32)
            slots = np.zeros(n_pad, np.int32)
            last_idx = np.zeros(n_pad, np.int32)
            for i in range(n_pad):
                req, slot = group[min(i, n - 1)]
                prompt = req["prompt"]
                tokens[i, :len(prompt)] = prompt
                slots[i] = slot
                last_idx[i] = len(prompt) - 1
            t0 = time.perf_counter()
            first, self.cache = self._prefill(
                self.params, jnp.asarray(tokens), self.cache,
                jnp.asarray(slots), jnp.asarray(last_idx))
            first = np.asarray(first)            # N ints, one transfer
            # The fetch syncs the dispatch, so this interval is the real
            # prefill cost — bench_serve derives prefill tokens/s from
            # it without decode/queueing time polluting the denominator,
            # and the XLA monitor turns it into achieved-FLOPs/bandwidth
            # gauges against this bucket's compiler cost analysis.
            prefill_wall = time.perf_counter() - t0
            self.prefill_seconds += prefill_wall
            self._prefill.note_execution(prefill_wall)
            self._prefill_shapes.add((n_pad, padded_len))
            true_tokens = int(last_idx[:n].sum()) + n
            self.prefill_batches += 1
            self.prefill_requests += n
            self.prefill_tokens += true_tokens
            mdefs.CB_PREFILL_REQUESTS.inc(n, tags=self._mtags)
            mdefs.CB_PREFILL_TOKENS.inc(true_tokens, tags=self._mtags)
            for (req, slot), tok in zip(group, first):
                tok = int(tok)
                if self.token_callback is not None:
                    self.token_callback(req["rid"], tok)
                self._slots[slot] = {
                    "rid": req["rid"], "out": [tok],
                    "max_new": req["max_new"],
                    "pos": len(req["prompt"]),   # next decode writes here
                    "last": tok,
                }
                self._maybe_finish(slot)
        self._dirty = True  # device tokens/positions need re-upload

    def _maybe_finish(self, slot: int) -> None:
        st = self._slots.get(slot)
        if st is None:
            return
        done = len(st["out"]) >= st["max_new"] or (
            self.eos_token is not None and st["out"][-1] == self.eos_token)
        if done:
            self._finished[st["rid"]] = st["out"]
            del self._slots[slot]
            self._free.append(slot)

    def _upload_state(self) -> None:
        tokens = np.zeros(self.num_slots, np.int32)
        positions = np.zeros(self.num_slots, np.int32)
        for slot, st in self._slots.items():
            tokens[slot] = st["last"]
            positions[slot] = st["pos"]
        self._d_tokens = jnp.asarray(tokens)
        self._d_positions = jnp.asarray(positions)
        self._dirty = False

    def _apply_tokens(self, nxt_rows, membership) -> bool:
        """Book one or more fetched tick rows; returns True when any
        request finished (membership changed)."""
        finished_any = False
        applied = 0
        for row in nxt_rows:
            for slot, rid in membership:
                st = self._slots.get(slot)
                if st is None or st["rid"] != rid:
                    continue  # finished earlier in this batch: skip tail
                tok = int(row[slot])
                if self.token_callback is not None:
                    self.token_callback(rid, tok)
                st["out"].append(tok)
                st["last"] = tok
                st["pos"] += 1
                applied += 1
                self._maybe_finish(slot)
                if slot not in self._slots:
                    finished_any = True
        if applied:
            from ray_tpu._private import metrics_defs as mdefs

            mdefs.CB_DECODE_TOKENS.inc(applied, tags=self._mtags)
        return finished_any

    def step(self) -> Dict[int, List[int]]:
        """Admit waiting requests, run one decode tick over all active
        slots, and return the requests that finished (with
        ``sync_every > 1``, finish detection lags up to 2K ticks)."""
        from ray_tpu._private import metrics_defs as mdefs

        active = len(self._slots)
        mdefs.CB_ACTIVE_SLOTS.set(active, tags=self._mtags)
        mdefs.CB_WAITING_REQUESTS.set(len(self._waiting), tags=self._mtags)
        mdefs.CB_SLOT_OCCUPANCY.set(active / max(self.num_slots, 1),
                                    tags=self._mtags)
        if self.sync_every == 1:
            self._admit()
            if self._slots:
                if self._dirty:
                    self._upload_state()
                t0 = time.perf_counter()
                self._d_tokens, self._d_positions, self.cache = self._tick(
                    self.params, self._d_tokens, self._d_positions,
                    self.cache)
                nxt = np.asarray(self._d_tokens)  # 4 bytes/slot
                # Per-tick sync: the fetch IS the device sync, so this is
                # the honest tick latency (dispatch + compute + fetch) —
                # also the denominator for the tick's achieved-FLOPs/
                # bandwidth gauges (cost_analysis over measured wall).
                tick_wall = time.perf_counter() - t0
                mdefs.CB_TICK_MS.observe(tick_wall * 1e3, tags=self._mtags)
                self._tick.note_execution(tick_wall)
                if self._apply_tokens(
                        [nxt], [(s, st["rid"])
                                for s, st in self._slots.items()]):
                    self._dirty = True
            out, self._finished = self._finished, {}
            return out
        return self._step_buffered()

    def _step_buffered(self) -> Dict[int, List[int]]:
        # Admission only at a clean boundary (no speculative ticks in
        # flight): an upload mid-buffer would rewind the device sequence.
        if not self._buf and self._pending is None:
            self._admit()
        if self._slots:
            if self._dirty and not self._buf and self._pending is None:
                self._upload_state()
            from ray_tpu._private import metrics_defs as mdefs

            t0 = time.perf_counter()
            self._d_tokens, self._d_positions, self.cache = self._tick(
                self.params, self._d_tokens, self._d_positions, self.cache)
            # Buffered mode overlaps fetches with compute, so this is
            # dispatch time only; steady-state backpressure still makes
            # the histogram track the real tick cadence.
            mdefs.CB_TICK_MS.observe(
                (time.perf_counter() - t0) * 1e3, tags=self._mtags)
            self._buf.append(self._d_tokens)
        want_admit = bool(self._waiting and self._free)
        if len(self._buf) >= self.sync_every or want_admit or (
                not self._slots and (self._buf or self._pending is not None)):
            # Non-K arms drain in-flight state early: a waiting request
            # with a free slot must not starve behind steady pipelining
            # (time-to-first-token), and a cancel of the last request
            # must not wedge admission.
            self._flush_buffered(force_boundary=want_admit)
        out, self._finished = self._finished, {}
        return out

    def _flush_buffered(self, force_boundary: bool = False) -> None:
        # 1. Apply the PRIOR pending fetch first — its transfer has been
        # overlapping the ticks just buffered. If it finished requests,
        # the current buffer is stale speculation over freed slots:
        # discard it and rewind (re-upload host state next step).
        if self._pending is not None:
            stacked, membership = self._pending
            self._pending = None
            rows = np.asarray(stacked)  # overlapped: usually ready
            if self._apply_tokens(list(rows), membership):
                self._buf = []
                self._dirty = True
                return
        if force_boundary and self._buf:
            # A waiting request needs a clean boundary to admit: apply the
            # just-stacked-would-be buffer SYNCHRONOUSLY instead of
            # pipelining it, then rewind so the next step re-admits.
            rows = np.asarray(jnp.stack(self._buf))
            membership = [(s, st["rid"]) for s, st in self._slots.items()]
            self._buf = []
            self._apply_tokens(list(rows), membership)
            self._dirty = True
            return
        if not self._buf:
            return
        # 2. Stack this buffer into ONE transfer and start it async; it
        # lands while the next K ticks run.
        stacked = jnp.stack(self._buf)
        self._buf = []
        try:
            stacked.copy_to_host_async()
        except Exception:  # noqa: BLE001 — platform without async copy
            pass
        self._pending = (stacked,
                         [(s, st["rid"]) for s, st in self._slots.items()])

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drive ticks until every submitted request finished."""
        results: Dict[int, List[int]] = {}
        while self.has_work():
            results.update(self.step())
        return results
