"""Mixtral-family sparse-MoE decoder: the second native model family.

Reference: the reference framework hosts MoE models via external engines
(SURVEY.md §2.3 — vLLM under ``python/ray/llm``); ray_tpu ships the model
natively, TPU-first. The attention backbone, remat policy, scan layer
stack, and GSPMD sharding constraints are the Llama ones
(:mod:`ray_tpu.models.llama` with an ``mlp_fn`` hook) — this module swaps
every dense SwiGLU block for a top-k routed expert layer
(:func:`ray_tpu.ops.moe.moe_layer`) whose stacked expert weights carry the
``experts`` logical axis, so dispatch/combine lower to ICI all-to-alls
when the mesh has an ``expert`` axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models import llama
from ray_tpu.ops.moe import moe_layer

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336   # per-expert FFN width
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 32768
    rope_theta: float = 1e6
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    remat_policy: str = "full"
    attention: str = "auto"
    # MoE
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_coeff: float = 0.02          # router load-balancing weight

    @staticmethod
    def mixtral_8x7b(**kw) -> "MixtralConfig":
        return MixtralConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "MixtralConfig":
        """CPU-runnable config for tests."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 96)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_experts", 4)
        kw.setdefault("top_k", 2)
        kw.setdefault("remat", False)
        return MixtralConfig(**kw)

    def backbone(self) -> llama.LlamaConfig:
        """The Llama config driving the shared attention backbone."""
        return llama.LlamaConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_layers=self.num_layers, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, head_dim=self.head_dim,
            max_seq_len=self.max_seq_len, rope_theta=self.rope_theta,
            rms_eps=self.rms_eps, dtype=self.dtype, remat=self.remat,
            remat_policy=self.remat_policy, attention=self.attention)


def logical_axes(config: MixtralConfig) -> Params:
    """Pytree of logical-axis tuples matching :func:`init_params`."""
    axes = llama.logical_axes(config.backbone())
    layer = dict(axes["layers"])
    for k in ("w_gate", "w_up", "w_down"):
        del layer[k]
    layer.update({
        "w_router": ("layers", "embed", None),
        "moe_gate": ("layers", "experts", "embed", "mlp"),
        "moe_up": ("layers", "experts", "embed", "mlp"),
        "moe_down": ("layers", "experts", "mlp", "embed"),
    })
    axes["layers"] = layer
    return axes


def init_params(config: MixtralConfig, key: jax.Array) -> Params:
    c = config
    k_backbone, k_router, kg, ku, kd = jax.random.split(key, 5)
    params = llama.init_params(c.backbone(), k_backbone)
    layers = dict(params["layers"])
    for k in ("w_gate", "w_up", "w_down"):
        del layers[k]
    L, E, M, X = c.num_layers, c.hidden_size, c.intermediate_size, \
        c.num_experts
    scale_in, scale_out = E ** -0.5, M ** -0.5
    layers["w_router"] = (jax.random.normal(k_router, (L, E, X))
                          * scale_in).astype(jnp.float32)
    layers["moe_gate"] = (jax.random.normal(kg, (L, X, E, M))
                          * scale_in).astype(c.dtype)
    layers["moe_up"] = (jax.random.normal(ku, (L, X, E, M))
                        * scale_in).astype(c.dtype)
    layers["moe_down"] = (jax.random.normal(kd, (L, X, M, E))
                          * scale_out).astype(c.dtype)
    params["layers"] = layers
    return params


def _moe_mlp(config: MixtralConfig):
    c = config

    def mlp_fn(h, layer):
        out, aux = moe_layer(
            h,
            {"w_router": layer["w_router"],
             "w_gate": layer["moe_gate"],
             "w_up": layer["moe_up"],
             "w_down": layer["moe_down"]},
            num_experts=c.num_experts, top_k=c.top_k,
            capacity_factor=c.capacity_factor)
        return out, aux["aux_loss"]

    return mlp_fn


def forward(params: Params, tokens: jnp.ndarray, config: MixtralConfig,
            mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """Logits [B, S, V] (fp32)."""
    return llama.forward(params, tokens, config.backbone(), mesh,
                         mlp_fn=_moe_mlp(config))


def loss_fn(params: Params, batch: Dict[str, jnp.ndarray],
            config: MixtralConfig, mesh: Optional[Mesh] = None,
            vocab_chunks: int = 8
            ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Next-token CE + router load-balancing aux loss."""
    return llama.loss_fn(params, batch, config.backbone(), mesh,
                         vocab_chunks=vocab_chunks,
                         mlp_fn=_moe_mlp(config),
                         aux_coeff=config.aux_coeff)


def num_params(config: MixtralConfig) -> int:
    c = config
    attn = (2 * c.hidden_size
            + c.hidden_size * c.num_heads * c.head_dim * 2
            + c.hidden_size * c.num_kv_heads * c.head_dim * 2)
    moe = (c.hidden_size * c.num_experts
           + 3 * c.num_experts * c.hidden_size * c.intermediate_size)
    return (c.vocab_size * c.hidden_size * 2 + c.hidden_size
            + c.num_layers * (attn + moe))


def active_params(config: MixtralConfig) -> int:
    """Per-token active parameters (top_k experts of num_experts)."""
    c = config
    attn = (2 * c.hidden_size
            + c.hidden_size * c.num_heads * c.head_dim * 2
            + c.hidden_size * c.num_kv_heads * c.head_dim * 2)
    moe = (c.hidden_size * c.num_experts
           + 3 * c.top_k * c.hidden_size * c.intermediate_size)
    return (c.vocab_size * c.hidden_size * 2 + c.hidden_size
            + c.num_layers * (attn + moe))
