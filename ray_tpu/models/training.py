"""Sharded training step for ray_tpu models.

Builds the jitted GSPMD train step the Train library and the benchmarks run:
parameters/optimizer state are sharded by the logical-axis rule table
(:mod:`ray_tpu.parallel.sharding`), the batch is sharded over the data axes,
and XLA inserts all collectives (reduce-scatter/all-gather for FSDP, psum for
DP) — the TPU-native equivalent of the reference's DDP/FSDP wrappers
(reference: ``python/ray/train/torch/train_loop_utils.py:162-201``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private import xla_monitor
from ray_tpu.models import llama
from ray_tpu.parallel import sharding as shd


@dataclasses.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(step=c[0], params=c[1], opt_state=c[2]),
)


def _divisible_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Remove mesh axes from a PartitionSpec where they don't divide the dim."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def axis_size(entry) -> int:
        names = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for name in names:
            n *= sizes.get(name, 1)
        return n

    fixed = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if entry is None or dim % axis_size(entry) == 0:
            fixed.append(entry)
        else:
            fixed.append(None)
    return P(*fixed)


def _spec_tree_for_state(state_shapes, params_treedef, param_specs):
    """Map PartitionSpecs onto an arbitrary (optax) state pytree.

    Any subtree structurally identical to the params pytree gets the param
    specs (optimizer moments mirror params); every other leaf is replicated.
    """

    def visit(node):
        try:
            if jax.tree.structure(node) == params_treedef:
                return param_specs
        except Exception:
            pass
        if hasattr(node, "_fields"):  # namedtuple (optax states)
            return type(node)(*[visit(x) for x in node])
        if isinstance(node, tuple):
            return tuple(visit(x) for x in node)
        if isinstance(node, list):
            return [visit(x) for x in node]
        if isinstance(node, dict):
            return {k: visit(v) for k, v in node.items()}
        return P()  # scalar leaf (e.g. count) — replicated

    return visit(state_shapes)


def default_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10000,
    mu_dtype=None,
) -> optax.GradientTransformation:
    """AdamW with warmup-cosine.

    Moment dtypes: optax inits BOTH moments in the params' dtype — with
    bf16 params (this framework's default) the default optimizer state is
    already bf16 mu AND bf16 nu. ``mu_dtype`` can RAISE the first
    moment's precision (e.g. ``jnp.float32`` for bf16 params) at
    +4 bytes/param; note the second moment has no such knob in optax and
    stays in the params' dtype.
    """
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


class ShardedTrainer:
    """Compiled sharded train step + state management for one model family.

    ``rules`` defaults to :data:`ray_tpu.parallel.sharding.DEFAULT_RULES`
    (FSDP on embed, TP on heads/mlp/vocab, batch over (data, fsdp)).
    """

    def __init__(
        self,
        config: llama.LlamaConfig,
        mesh: Mesh,
        optimizer: Optional[optax.GradientTransformation] = None,
        rules: Optional[shd.LogicalRules] = None,
    ):
        self.config = config
        self.mesh = mesh
        self.rules = rules
        self.optimizer = optimizer or default_optimizer()

        axes = llama.logical_axes(config)
        param_specs = shd.tree_specs(axes, rules)
        param_shapes = jax.eval_shape(
            functools.partial(llama.init_params, config), jax.random.PRNGKey(0)
        )
        # Drop mesh axes that do not divide the corresponding dim (e.g. 2 kv
        # heads on a tensor=4 mesh): those dims stay replicated, matching
        # GSPMD's divisibility requirement.
        self.param_specs = jax.tree.map(
            lambda spec, shape: _divisible_spec(spec, shape.shape, mesh),
            param_specs, param_shapes,
        )
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.param_specs
        )
        self.batch_spec = P(("data", "fsdp"))
        self.batch_sharding = NamedSharding(mesh, self.batch_spec)
        self._build()

    def _build(self):
        config, mesh, optimizer = self.config, self.mesh, self.optimizer

        def init_fn(key):
            params = llama.init_params(config, key)
            params = jax.tree.map(
                jax.lax.with_sharding_constraint, params, self.param_shardings
            )
            opt_state = optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state)

        # Derive opt-state shardings structurally, then jit init with explicit
        # output shardings so even the first state materializes sharded
        # (never a full replica per host).
        state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        params_treedef = jax.tree.structure(
            jax.eval_shape(functools.partial(llama.init_params, config),
                           jax.random.PRNGKey(0))
        )
        opt_specs = _spec_tree_for_state(
            state_shapes.opt_state, params_treedef, self.param_specs
        )
        self.state_shardings = TrainState(
            step=NamedSharding(mesh, P()),
            params=self.param_shardings,
            opt_state=jax.tree.map(
                lambda s: NamedSharding(mesh, s), opt_specs,
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        self._init = xla_monitor.instrument(
            init_fn, name="train_init", shape_policy="free",
            out_shardings=self.state_shardings)

        def step_fn(state: TrainState, batch: Dict[str, jnp.ndarray]):
            def loss(params):
                return llama.loss_fn(params, batch, config, mesh)

            (loss_val, metrics), grads = jax.value_and_grad(
                loss, has_aux=True
            )(state.params)
            updates, new_opt = optimizer.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            new_params = jax.tree.map(
                jax.lax.with_sharding_constraint, new_params, self.param_shardings
            )
            new_state = TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            )
            metrics = dict(metrics)
            metrics["grad_norm"] = optax.global_norm(grads)
            return new_state, metrics

        # One legitimate signature per trainer: a second compile means
        # the batch shape churned (a classic silent-retrace source in
        # training loops) and raises ray_tpu_xla_retraces_total. Step
        # cadence feeds the achieved-FLOPs/MFU gauges — honest whenever
        # the loop syncs per step (fetching the loss does).
        self._step = xla_monitor.instrument(
            step_fn,
            name="train_step",
            in_shardings=(self.state_shardings,
                          {"tokens": self.batch_sharding,
                           "mask": self.batch_sharding}),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )

    # -- public API --------------------------------------------------------
    def init_state(self, seed: int = 0) -> TrainState:
        with self.mesh:
            return self._init(jax.random.PRNGKey(seed))

    def train_step(
        self, state: TrainState, batch: Dict[str, jnp.ndarray]
    ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        with self.mesh:
            return self._step(state, batch)

    def shard_batch(self, batch: Dict[str, jnp.ndarray]):
        return jax.tree.map(
            lambda x: jax.device_put(x, self.batch_sharding), batch
        )

    # -- checkpoint plane hooks --------------------------------------------
    def save_state(self, plane, state: TrainState, step: Optional[int] = None):
        """Async-save ``state`` through a checkpoint plane
        (:class:`ray_tpu.checkpoint.CheckpointPlane`). The device→host
        handoff happens before this returns; serialization + write +
        manifest commit run in the background. Returns the SaveHandle."""
        if step is None:
            step = int(state.step)  # syncs the step scalar only
        return plane.save_async(int(step), state)

    def restore_state(self, plane, step: Optional[int] = None) -> TrainState:
        """Restore a committed checkpoint onto THIS trainer's mesh layout.

        The saving topology is irrelevant: shards are reassembled and
        re-sharded per ``self.state_shardings`` (elastic restore — save on
        ``fsdp=8``, restore on ``fsdp=4×tp=2`` is bit-identical)."""
        with self.mesh:
            return plane.restore(self.state_shardings, step=step)


def synthetic_batch(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Dict[str, jnp.ndarray]:
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (batch_size, seq_len), 0, vocab_size, jnp.int32)
    return {"tokens": tokens, "mask": jnp.ones_like(tokens)}
