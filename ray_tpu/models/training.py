"""Sharded training step for ray_tpu models.

Builds the jitted GSPMD train step the Train library and the benchmarks run:
parameters/optimizer state are sharded by the logical-axis rule table
(:mod:`ray_tpu.parallel.sharding`), the batch is sharded over the data axes,
and XLA inserts all collectives (reduce-scatter/all-gather for FSDP, psum for
DP) — the TPU-native equivalent of the reference's DDP/FSDP wrappers
(reference: ``python/ray/train/torch/train_loop_utils.py:162-201``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private import xla_monitor
from ray_tpu.models import llama
from ray_tpu.parallel import sharding as shd


@dataclasses.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(step=c[0], params=c[1], opt_state=c[2]),
)


def _divisible_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Remove mesh axes from a PartitionSpec where they don't divide the dim."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def axis_size(entry) -> int:
        names = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for name in names:
            n *= sizes.get(name, 1)
        return n

    fixed = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if entry is None or dim % axis_size(entry) == 0:
            fixed.append(entry)
        else:
            fixed.append(None)
    return P(*fixed)


def _spec_tree_for_state(state_shapes, params_treedef, param_specs):
    """Map PartitionSpecs onto an arbitrary (optax) state pytree.

    Any subtree structurally identical to the params pytree gets the param
    specs (optimizer moments mirror params); every other leaf is replicated.
    """

    def visit(node):
        try:
            if jax.tree.structure(node) == params_treedef:
                return param_specs
        except Exception:
            pass
        if hasattr(node, "_fields"):  # namedtuple (optax states)
            return type(node)(*[visit(x) for x in node])
        if isinstance(node, tuple):
            return tuple(visit(x) for x in node)
        if isinstance(node, list):
            return [visit(x) for x in node]
        if isinstance(node, dict):
            return {k: visit(v) for k, v in node.items()}
        return P()  # scalar leaf (e.g. count) — replicated

    return visit(state_shapes)


def default_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10000,
    mu_dtype=None,
) -> optax.GradientTransformation:
    """AdamW with warmup-cosine.

    Moment dtypes: optax inits BOTH moments in the params' dtype — with
    bf16 params (this framework's default) the default optimizer state is
    already bf16 mu AND bf16 nu. ``mu_dtype`` can RAISE the first
    moment's precision (e.g. ``jnp.float32`` for bf16 params) at
    +4 bytes/param; note the second moment has no such knob in optax and
    stays in the params' dtype.
    """
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


class ShardedTrainer:
    """Compiled sharded train step + state management for one model family.

    ``rules`` defaults to :data:`ray_tpu.parallel.sharding.DEFAULT_RULES`
    (FSDP on embed, TP on heads/mlp/vocab, batch over (data, fsdp)).
    """

    def __init__(
        self,
        config: llama.LlamaConfig,
        mesh: Mesh,
        optimizer: Optional[optax.GradientTransformation] = None,
        rules: Optional[shd.LogicalRules] = None,
        microbatches: int = 1,
        grad_accum_dtype: Any = None,
    ):
        self.config = config
        self.mesh = mesh
        self.rules = rules
        self.optimizer = optimizer or default_optimizer()
        # Gradient-accumulation microbatching: the jitted step lax.scans
        # over M microbatches (token-weighted grad accumulation, ONE
        # optimizer update) so the global batch scales for DCN without a
        # second compiled signature. M=1 keeps the direct path.
        # ``grad_accum_dtype`` is the accumulator precision: fp32 by
        # default (bf16 += over M terms drops low bits); pass the param
        # dtype to halve the carry's HBM at memory-bound shapes.
        self.microbatches = max(int(microbatches), 1)
        self.grad_accum_dtype = grad_accum_dtype or jnp.float32

        axes = llama.logical_axes(config)
        param_specs = shd.tree_specs(axes, rules)
        param_shapes = jax.eval_shape(
            functools.partial(llama.init_params, config), jax.random.PRNGKey(0)
        )
        # Drop mesh axes that do not divide the corresponding dim (e.g. 2 kv
        # heads on a tensor=4 mesh): those dims stay replicated, matching
        # GSPMD's divisibility requirement.
        self.param_specs = jax.tree.map(
            lambda spec, shape: _divisible_spec(spec, shape.shape, mesh),
            param_specs, param_shapes,
        )
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.param_specs
        )
        self.batch_spec = P(("data", "fsdp"))
        self.batch_sharding = NamedSharding(mesh, self.batch_spec)
        self._build()

    def _build(self):
        config, mesh, optimizer = self.config, self.mesh, self.optimizer

        def init_fn(key):
            params = llama.init_params(config, key)
            params = jax.tree.map(
                jax.lax.with_sharding_constraint, params, self.param_shardings
            )
            opt_state = optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state)

        # Derive opt-state shardings structurally, then jit init with explicit
        # output shardings so even the first state materializes sharded
        # (never a full replica per host).
        state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        params_treedef = jax.tree.structure(
            jax.eval_shape(functools.partial(llama.init_params, config),
                           jax.random.PRNGKey(0))
        )
        opt_specs = _spec_tree_for_state(
            state_shapes.opt_state, params_treedef, self.param_specs
        )
        self.state_shardings = TrainState(
            step=NamedSharding(mesh, P()),
            params=self.param_shardings,
            opt_state=jax.tree.map(
                lambda s: NamedSharding(mesh, s), opt_specs,
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        self._init = xla_monitor.instrument(
            init_fn, name="train_init", shape_policy="free",
            out_shardings=self.state_shardings)

        M = self.microbatches

        def _grads_direct(params, batch):
            def loss(p):
                return llama.loss_fn(p, batch, config, mesh)

            (loss_val, metrics), grads = jax.value_and_grad(
                loss, has_aux=True
            )(params)
            metrics = dict(metrics)
            return loss_val, metrics, grads

        def _grads_microbatched(params, batch):
            """lax.scan over M microbatches with token-weighted grad
            accumulation — the summed grads equal the single-big-batch
            grads EXACTLY (up to fp reduction order): each microbatch's
            mean loss is rescaled by tokens_i/total so grad sums, not
            averages, reproduce d(nll_total/total)/dparams regardless of
            per-microbatch mask imbalance."""
            tokens = batch["tokens"]
            g = tokens.shape[0]
            if g % M:
                raise ValueError(
                    f"global batch {g} not divisible by "
                    f"microbatches={M}")
            mask = batch.get("mask")
            m_full = (mask[:, 1:] if mask is not None else
                      jnp.ones_like(tokens[:, 1:])).astype(jnp.float32)
            total = jnp.maximum(jnp.sum(m_full), 1.0)

            def to_micro(x):
                mb = x.reshape((M, g // M) + x.shape[1:])
                spec = _divisible_spec(
                    P(None, ("data", "fsdp")), mb.shape, mesh)
                return jax.lax.with_sharding_constraint(
                    mb, NamedSharding(mesh, spec))

            micro = jax.tree.map(to_micro, batch)

            def body(carry, mb):
                gsum, loss_sum, correct_sum = carry

                def scaled(p):
                    loss, metrics = llama.loss_fn(p, mb, config, mesh)
                    # loss_i * tokens_i = nll_sum_i; /total makes the
                    # M-term SUM equal the big-batch mean loss.
                    return loss * (metrics["tokens"] / total), metrics

                (loss_i, metrics_i), grads_i = jax.value_and_grad(
                    scaled, has_aux=True)(params)
                # grad_accum_dtype (default fp32) accumulation: bf16 +=
                # over M terms loses low bits the single-batch step keeps.
                gsum = jax.tree.map(
                    lambda a, b: a + b.astype(a.dtype), gsum, grads_i)
                correct = metrics_i["accuracy"] * metrics_i["tokens"]
                return (gsum, loss_sum + loss_i,
                        correct_sum + correct), None

            gzero = jax.tree.map(
                lambda p: jnp.zeros(p.shape, self.grad_accum_dtype),
                params)
            (gsum, loss_val, correct_sum), _ = jax.lax.scan(
                body, (gzero, jnp.zeros(()), jnp.zeros(())), micro)
            grads = jax.tree.map(
                lambda acc, p: acc.astype(p.dtype), gsum, params)
            metrics = {"loss": loss_val,
                       "accuracy": correct_sum / total,
                       "tokens": total}
            return loss_val, metrics, grads

        def step_fn(state: TrainState, batch: Dict[str, jnp.ndarray]):
            compute = _grads_direct if M == 1 else _grads_microbatched
            loss_val, metrics, grads = compute(state.params, batch)
            updates, new_opt = optimizer.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            new_params = jax.tree.map(
                jax.lax.with_sharding_constraint, new_params, self.param_shardings
            )
            new_state = TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            )
            metrics["grad_norm"] = optax.global_norm(grads)
            return new_state, metrics

        # One legitimate signature per trainer: a second compile means
        # the batch shape churned (a classic silent-retrace source in
        # training loops) and raises ray_tpu_xla_retraces_total.
        # Microbatching lives INSIDE this signature (the scan count is a
        # closure constant), so M never multiplies compiled programs.
        # Achieved-FLOPs/MFU gauges: the call-cadence fallback is only
        # honest when the loop syncs per step (fetching the loss does);
        # async loops (ray_tpu.train.loop.AsyncStepLoop) instead feed
        # measured window wall time via self._step.note_execution, the
        # same windowed accounting the buffered serve engine uses.
        self._step = xla_monitor.instrument(
            step_fn,
            name="train_step",
            in_shardings=(self.state_shardings,
                          {"tokens": self.batch_sharding,
                           "mask": self.batch_sharding}),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )

    # -- public API --------------------------------------------------------
    def init_state(self, seed: int = 0) -> TrainState:
        with self.mesh:
            return self._init(jax.random.PRNGKey(seed))

    def train_step(
        self, state: TrainState, batch: Dict[str, jnp.ndarray]
    ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        g = batch["tokens"].shape[0]
        if g % self.microbatches:
            raise ValueError(
                f"global batch {g} not divisible by "
                f"microbatches={self.microbatches}")
        with self.mesh:
            return self._step(state, batch)

    def shard_batch(self, batch: Dict[str, jnp.ndarray]):
        return jax.tree.map(
            lambda x: jax.device_put(x, self.batch_sharding), batch
        )

    # -- checkpoint plane hooks --------------------------------------------
    def save_state(self, plane, state: TrainState, step: Optional[int] = None):
        """Async-save ``state`` through a checkpoint plane
        (:class:`ray_tpu.checkpoint.CheckpointPlane`). The device→host
        handoff happens before this returns; serialization + write +
        manifest commit run in the background. Returns the SaveHandle."""
        if step is None:
            step = int(state.step)  # syncs the step scalar only
        return plane.save_async(int(step), state)

    def restore_state(self, plane, step: Optional[int] = None) -> TrainState:
        """Restore a committed checkpoint onto THIS trainer's mesh layout.

        The saving topology is irrelevant: shards are reassembled and
        re-sharded per ``self.state_shardings`` (elastic restore — save on
        ``fsdp=8``, restore on ``fsdp=4×tp=2`` is bit-identical)."""
        with self.mesh:
            return plane.restore(self.state_shardings, step=step)


def synthetic_batch(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Dict[str, jnp.ndarray]:
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (batch_size, seq_len), 0, vocab_size, jnp.int32)
    return {"tokens": tokens, "mask": jnp.ones_like(tokens)}
