"""In-device token sampling for the decode data plane.

Greedy argmax moved on-device in PR 2 (4 bytes/slot to host instead of
``[B, V]`` logits); this module moves the REST of sampling in-device so
temperature/top-p serving pays the same host traffic as greedy. The
sampler runs inside the donated-cache tick jit; randomness is derived
from a base seed and a device-threaded step counter (``fold_in``), so a
fixed seed replays bit-identically — including across the buffered
engine's speculative rewinds, which re-run the same step numbers with
the same live-slot state.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    """Engine-level sampling configuration (static per compiled tick:
    changing it recompiles, like any other engine knob).

    ``temperature <= 0`` means greedy argmax (the default; exempt from
    PRNG plumbing entirely). ``top_p`` keeps the smallest prefix of the
    sorted distribution whose cumulative probability covers ``top_p``
    (the first token always survives)."""

    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        # Reject degenerate configs at construction (YAML deploy configs
        # reach here): top_p <= 0 would mask EVERY logit to -inf and the
        # engine would silently stream token 0 forever.
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if not self.temperature >= 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0

    @classmethod
    def coerce(cls, value) -> "SamplingParams":
        """Accept SamplingParams | dict | None (deployment configs pass
        plain dicts through serve ``init_kwargs``)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"sampling must be SamplingParams or dict, "
                        f"got {type(value)}")


def sample_tokens(logits, key, temperature: float, top_p: float):
    """logits [B, V] fp32 -> sampled token ids [B] int32 (argmax when
    ``temperature <= 0``; ``temperature``/``top_p`` are python statics
    baked into the compiled program)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / temperature
    if top_p < 1.0:
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep tokens whose EXCLUSIVE cumulative mass is under top_p:
        # the head of the distribution always survives, ties at the
        # boundary are kept.
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                         axis=-1, keepdims=True)
        scaled = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
    # One key per step: categorical draws i.i.d. gumbel noise per [B, V]
    # element, so per-row draws are independent AND a row whose logits
    # and index repeat (speculative rewind replay) resamples the same
    # token.
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def step_key(seed: int, step, salt: int = 0):
    """Deterministic per-step PRNG key: base seed folded with the device
    step counter (and a salt separating tick vs prefill streams)."""
    key = jax.random.PRNGKey(seed)
    if salt:
        key = jax.random.fold_in(key, salt)
    return jax.random.fold_in(key, step)
