"""In-device token sampling for the decode data plane.

Greedy argmax moved on-device in PR 2 (4 bytes/slot to host instead of
``[B, V]`` logits); this module moves the REST of sampling in-device so
temperature/top-p serving pays the same host traffic as greedy. The
sampler runs inside the donated-cache tick jit; randomness is derived
from a base seed and a device-threaded step counter (``fold_in``), so a
fixed seed replays bit-identically — including across the buffered
engine's speculative rewinds, which re-run the same step numbers with
the same live-slot state.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    """Engine-level sampling configuration (static per compiled tick:
    changing it recompiles, like any other engine knob).

    ``temperature <= 0`` means greedy argmax (the default; exempt from
    PRNG plumbing entirely). ``top_p`` keeps the smallest prefix of the
    sorted distribution whose cumulative probability covers ``top_p``
    (the first token always survives)."""

    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        # Reject degenerate configs at construction (YAML deploy configs
        # reach here): top_p <= 0 would mask EVERY logit to -inf and the
        # engine would silently stream token 0 forever.
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if not self.temperature >= 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0

    @classmethod
    def coerce(cls, value) -> "SamplingParams":
        """Accept SamplingParams | dict | None (deployment configs pass
        plain dicts through serve ``init_kwargs``)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"sampling must be SamplingParams or dict, "
                        f"got {type(value)}")


def sample_tokens(logits, key, temperature: float, top_p: float):
    """logits [B, V] fp32 -> sampled token ids [B] int32 (argmax when
    ``temperature <= 0``; ``temperature``/``top_p`` are python statics
    baked into the compiled program)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / temperature
    if top_p < 1.0:
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep tokens whose EXCLUSIVE cumulative mass is under top_p:
        # the head of the distribution always survives, ties at the
        # boundary are kept.
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                         axis=-1, keepdims=True)
        scaled = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
    # One key per step: categorical draws i.i.d. gumbel noise per [B, V]
    # element, so per-row draws are independent AND a row whose logits
    # and index repeat (speculative rewind replay) resamples the same
    # token.
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def step_key(seed: int, step, salt: int = 0):
    """Deterministic per-step PRNG key: base seed folded with the device
    step counter (and a salt separating tick vs prefill streams)."""
    key = jax.random.PRNGKey(seed)
    if salt:
        key = jax.random.fold_in(key, salt)
    return jax.random.fold_in(key, step)


# Speculative-decode PRNG streams. Salt 0 is the plain decode tick and 1
# the prefill sampler; spec ticks never draw from salt 0, so a deployment
# that adapts k down to 0 re-enters the EXACT pre-spec sample sequence.
SPEC_DRAFT_SALT = 2    # drafter's proposal draws (one fold_in(i) per draft)
SPEC_ACCEPT_SALT = 3   # accept/reject uniforms
SPEC_FIX_SALT = 4      # residual resamples + the bonus token


def filtered_probs(logits, temperature: float, top_p: float):
    """The exact post-temperature/top-p distribution ``sample_tokens``
    draws from, as probability rows (softmax over the filtered scaled
    logits). Axis-generic over leading dims: [..., V] -> [..., V].

    Speculative rejection sampling needs the target's and drafter's
    FILTERED distributions — acceptance ratios against the raw softmax
    would not preserve what ``sample_tokens`` actually samples — so this
    mirrors its masking math to the letter (exclusive-cumsum keep,
    boundary ties kept)."""
    scaled = logits / temperature
    if top_p < 1.0:
        sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                         axis=-1, keepdims=True)
        scaled = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
    return jax.nn.softmax(scaled, axis=-1)


def spec_commit(draft_tokens, draft_probs, logits, step, sampling):
    """Per-slot speculative acceptance, entirely in-device.

    ``draft_tokens`` [B, k] int32 — the drafter's proposals d_1..d_k;
    ``draft_probs`` [B, k, V] — the filtered proposal rows each d_i was
    sampled from (ignored under greedy, pass None);
    ``logits`` [B, k+1, V] fp32 — target logits at the k+1 verified
    positions (window token i's logits condition on the committed prefix
    plus drafts d_1..d_i).

    Returns ``(committed [B, k+1] int32, counts [B] int32)`` with counts
    in [1, k+1]: each slot commits its accepted draft prefix plus one
    token the target produced itself (the correction at the first
    mismatch, or the bonus token when every draft survived). Entries past
    a slot's count are well-defined but meaningless; the host never reads
    them.

    Greedy: accept while d_i == argmax_i — the committed stream is the
    target's own greedy stream by construction, bit-identical to spec-off.

    Sampled (Leviathan et al. 2023): accept d_i with probability
    min(1, p_i(d_i)/q_i(d_i)); on the first rejection resample from the
    renormalized residual max(p_i - q_i, 0); a fully-accepted window
    draws the bonus from p_{k+1}. Marginally every committed token is
    distributed exactly as the target's own sampler — the drafter only
    changes HOW MANY commit per tick. All draws are keyed off
    (seed, step, salt) like the base tick, so buffered-engine rewinds
    replay the same acceptances bit-identically.
    """
    b, k1, _ = logits.shape
    k = k1 - 1
    if sampling.greedy:
        v = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if k == 0:
            return v, jnp.ones((b,), jnp.int32)
        match = (draft_tokens == v[:, :k]).astype(jnp.int32)
        accepts = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        return v, accepts + 1

    p = filtered_probs(logits, sampling.temperature, sampling.top_p)
    p_d = p[:, :k]                                              # [B, k, V]
    p_at = jnp.take_along_axis(
        p_d, draft_tokens[..., None], axis=-1)[..., 0]          # [B, k]
    q_at = jnp.take_along_axis(
        draft_probs, draft_tokens[..., None], axis=-1)[..., 0]
    u = jax.random.uniform(
        step_key(sampling.seed, step, salt=SPEC_ACCEPT_SALT), shape=(b, k))
    ratio = p_at / jnp.maximum(q_at, 1e-30)
    acc = (u < jnp.minimum(ratio, 1.0)).astype(jnp.int32)
    accepts = jnp.sum(jnp.cumprod(acc, axis=1), axis=1)         # [B]
    # Correction tokens: the residual distribution at each draft position
    # (p - q clipped and renormalized; degenerate q == p rows can only be
    # reached with acceptance probability 1, so falling back to p there
    # keeps the categorical finite without changing any outcome) and the
    # target's own p at the bonus position.
    resid = jnp.maximum(p_d - draft_probs, 0.0)
    total = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(total > 0, resid / jnp.maximum(total, 1e-30), p_d)
    fix_dist = jnp.concatenate([resid, p[:, k:]], axis=1)       # [B, k+1, V]
    fix = jax.random.categorical(
        step_key(sampling.seed, step, salt=SPEC_FIX_SALT),
        jnp.log(jnp.maximum(fix_dist, 1e-38)), axis=-1).astype(jnp.int32)
    idx = jnp.arange(k + 1)[None, :]
    padded = jnp.pad(draft_tokens, ((0, 0), (0, 1)))
    committed = jnp.where(idx < accepts[:, None], padded, fix)
    return committed, accepts + 1
