"""ray_tpu: a TPU-native distributed compute framework.

A ground-up re-design of the Ray programming model (tasks, actors, objects,
placement groups, and the ML libraries layered on top) for TPU hardware:
the scheduler treats TPU chips and ICI slice topology as first-class
resources, collective communication lowers to XLA collectives over ICI/DCN
instead of NCCL, training backends shard models with GSPMD/``pjit``, and
long-context sequence parallelism (ring attention, Ulysses all-to-all) is
provided natively via pallas kernels and ``shard_map``.

Public API parity target: ``ray.*`` (reference: ``python/ray/__init__.py``).
"""

from ray_tpu import exceptions
from ray_tpu._private.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    list_named_actors,
    nodes,
    put,
    shutdown,
    wait,
)
from ray_tpu.actor import exit_actor, method
from ray_tpu.remote_function import make_remote as remote
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "ObjectRef",
    "ObjectRefGenerator",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "exit_actor",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "list_named_actors",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
    "__version__",
]
