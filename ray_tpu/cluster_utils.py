"""Multi-node test clusters on one host.

Reference: ``python/ray/cluster_utils.py:135`` (``Cluster.add_node`` :202,
``remove_node`` :286) — the fixture the reference uses for multi-node and
kill/failover tests without real machines. Here the GCS and node managers run
in-process (each with a real gRPC server); worker processes are real OS
subprocesses, so task execution crosses real process boundaries.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.gcs.server import GcsServer
from ray_tpu._private.node_manager.server import NodeManager


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None,
                 gcs_persist_path: Optional[str] = None):
        self.gcs_persist_path = gcs_persist_path
        self.gcs = GcsServer(port=0, persist_path=gcs_persist_path)
        self.address = f"127.0.0.1:{self.gcs.port}"
        self.nodes: List[NodeManager] = []
        self.head_node: Optional[NodeManager] = None
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))

    def restart_gcs(self) -> None:
        """Kill and restart the GCS on the same port (fault-tolerance tests:
        reference ``python/ray/tests/test_gcs_fault_tolerance.py``)."""
        port = self.gcs.port
        self.gcs.shutdown()
        self.gcs = GcsServer(port=port, persist_path=self.gcs_persist_path)

    def add_node(self, num_cpus: float = 4, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 **kwargs) -> NodeManager:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        node = NodeManager(self.address, resources=res, labels=labels)
        self.nodes.append(node)
        return node

    def remove_node(self, node: NodeManager, allow_graceful: bool = True):
        node.shutdown(graceful=allow_graceful)
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, timeout_s: float = 30.0) -> None:
        from ray_tpu._private import rpc
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        want = len(self.nodes)
        gcs = rpc.get_stub("GcsService", self.address)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            alive = [n for n in gcs.GetNodes(pb.GetNodesRequest()).nodes
                     if n.alive]
            if len(alive) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {want} alive nodes")

    def shutdown(self):
        for node in list(self.nodes):
            self.remove_node(node)
        self.gcs.shutdown()
