"""ray_tpu.dag: lazy task/actor DAGs + compiled execution.

Reference: ``python/ray/dag`` (SURVEY.md §2.3 aDAG) — ``.bind()`` builds a
lazy graph, ``.execute()`` submits it, and ``experimental_compile`` turns a
static graph into a reusable executable whose channels avoid per-call
(re)submission overhead. TPU-native perspective: a compiled ray_tpu DAG over
actors is the *host-side* orchestration analog of one jitted XLA program —
per-chip programs are already fused by jit; this layer chains multi-actor
pipelines (e.g. pipeline-parallel stages) with the minimum per-step control
overhead, mirroring how aDAG's NCCL channels chain GPU stages.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private.object_ref import ObjectRef


class DAGNode:
    """Base lazy node. ``execute`` submits the whole upstream graph."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- graph plumbing ----------------------------------------------------
    def _resolve_arg(self, arg, cache: Dict[int, Any]):
        if isinstance(arg, DAGNode):
            return arg._execute_cached(cache)
        return arg

    def _resolved(self, cache: Dict[int, Any]):
        args = tuple(self._resolve_arg(a, cache) for a in self._bound_args)
        kwargs = {k: self._resolve_arg(v, cache)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_cached(self, cache: Dict[int, Any]):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(cache)
        return cache[key]

    def _execute_impl(self, cache: Dict[int, Any]):
        raise NotImplementedError

    def execute(self, *input_args, **input_kwargs):
        cache: Dict[int, Any] = {
            id(n): v for n, v in zip(_collect_input_nodes(self), input_args)}
        return self._execute_cached(cache)

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for per-execution input (reference: ``ray.dag.InputNode``)."""

    _tls = threading.local()

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, cache):
        raise ValueError("InputNode value missing: pass it to execute(...)")


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, cache):
        args, kwargs = self._resolved(cache)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """Lazy actor construction; methods of the (future) actor can be bound."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None
        self._lock = threading.Lock()

    def _ensure_actor(self, cache):
        with self._lock:
            if self._handle is None:
                args, kwargs = self._resolved(cache)
                self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle

    def _execute_impl(self, cache):
        return self._ensure_actor(cache)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ActorMethodNode":
        return ActorMethodNode(self._class_node, self._method, args, kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, target, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._target = target  # ActorHandle or ClassNode
        self._method_name = method_name

    def _execute_impl(self, cache):
        args, kwargs = self._resolved(cache)
        target = self._target
        if isinstance(target, ClassNode):
            target = target._ensure_actor(cache)
        return getattr(target, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    def __init__(self, nodes: List[DAGNode]):
        super().__init__(tuple(nodes), {})

    def _execute_impl(self, cache):
        return [self._resolve_arg(n, cache) for n in self._bound_args]


def _collect_input_nodes(root: DAGNode) -> List[InputNode]:
    seen: List[InputNode] = []
    visited = set()

    def visit(node):
        if id(node) in visited:
            return
        visited.add(id(node))
        if isinstance(node, InputNode) and node not in seen:
            seen.append(node)
        for a in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                visit(a)

    visit(root)
    return seen


class CompiledDAG:
    """Reusable executable of a static DAG (reference:
    ``dag/compiled_dag_node.py:767``). Actors are created once at compile
    time; each ``execute`` only submits the per-call method chain."""

    def __init__(self, root: DAGNode):
        self._root = root
        # Materialize all ClassNodes now (actor startup off the hot path).
        warm: Dict[int, Any] = {}
        for node in _walk(root):
            if isinstance(node, ClassNode):
                node._ensure_actor(warm)

    def execute(self, *input_args) -> Any:
        return self._root.execute(*input_args)

    def teardown(self):
        for node in _walk(self._root):
            if isinstance(node, ClassNode) and node._handle is not None:
                try:
                    ray_tpu.kill(node._handle)
                except Exception:  # noqa: BLE001
                    pass


def _walk(root: DAGNode):
    visited = set()
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        yield node
        for a in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                stack.append(a)
        if isinstance(node, ActorMethodNode) and \
                isinstance(node._target, ClassNode):
            stack.append(node._target)


__all__ = [
    "ActorMethodNode", "ClassNode", "CompiledDAG", "DAGNode", "FunctionNode",
    "InputNode", "MultiOutputNode",
]
