"""ray_tpu.dag: lazy task/actor DAGs + compiled execution.

Reference: ``python/ray/dag`` (SURVEY.md §2.3 aDAG) — ``.bind()`` builds a
lazy graph, ``.execute()`` submits it, and ``experimental_compile`` turns a
static graph into a reusable executable whose channels avoid per-call
(re)submission overhead. TPU-native perspective: a compiled ray_tpu DAG over
actors is the *host-side* orchestration analog of one jitted XLA program —
per-chip programs are already fused by jit; this layer chains multi-actor
pipelines (e.g. pipeline-parallel stages) with the minimum per-step control
overhead, mirroring how aDAG's NCCL channels chain GPU stages.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private.object_ref import ObjectRef


class DAGNode:
    """Base lazy node. ``execute`` submits the whole upstream graph."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- graph plumbing ----------------------------------------------------
    def _resolve_arg(self, arg, cache: Dict[int, Any]):
        if isinstance(arg, DAGNode):
            return arg._execute_cached(cache)
        return arg

    def _resolved(self, cache: Dict[int, Any]):
        args = tuple(self._resolve_arg(a, cache) for a in self._bound_args)
        kwargs = {k: self._resolve_arg(v, cache)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_cached(self, cache: Dict[int, Any]):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(cache)
        return cache[key]

    def _execute_impl(self, cache: Dict[int, Any]):
        raise NotImplementedError

    def execute(self, *input_args, **input_kwargs):
        cache: Dict[int, Any] = {
            id(n): v for n, v in zip(_collect_input_nodes(self), input_args)}
        return self._execute_cached(cache)

    def experimental_compile(self, _buffer_size_bytes: Optional[int] = None) \
            -> "CompiledDAG":
        return CompiledDAG(self, buffer_size_bytes=_buffer_size_bytes)


class InputNode(DAGNode):
    """Placeholder for per-execution input (reference: ``ray.dag.InputNode``)."""

    _tls = threading.local()

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, cache):
        raise ValueError("InputNode value missing: pass it to execute(...)")


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, cache):
        args, kwargs = self._resolved(cache)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """Lazy actor construction; methods of the (future) actor can be bound."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None
        self._lock = threading.Lock()

    def _ensure_actor(self, cache):
        with self._lock:
            if self._handle is None:
                args, kwargs = self._resolved(cache)
                self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle

    def _execute_impl(self, cache):
        return self._ensure_actor(cache)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ActorMethodNode":
        return ActorMethodNode(self._class_node, self._method, args, kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, target, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._target = target  # ActorHandle or ClassNode
        self._method_name = method_name

    def _execute_impl(self, cache):
        args, kwargs = self._resolved(cache)
        target = self._target
        if isinstance(target, ClassNode):
            target = target._ensure_actor(cache)
        return getattr(target, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    def __init__(self, nodes: List[DAGNode]):
        super().__init__(tuple(nodes), {})

    def _execute_impl(self, cache):
        return [self._resolve_arg(n, cache) for n in self._bound_args]


def _collect_input_nodes(root: DAGNode) -> List[InputNode]:
    seen: List[InputNode] = []
    visited = set()

    def visit(node):
        if id(node) in visited:
            return
        visited.add(id(node))
        if isinstance(node, InputNode) and node not in seen:
            seen.append(node)
        for a in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                visit(a)

    visit(root)
    return seen


class _UnsupportedDAG(Exception):
    """Graph shape the channel compiler can't pin; interpreted fallback."""


class CompiledDAGRef:
    """Result handle of one compiled execution (reference:
    ``CompiledDAGRef``): ``get()`` blocks on the DAG's output channel."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = None):
        return self._dag._get_result(self._seq, timeout)


class CompiledDAG:
    """Reusable executable of a static DAG (reference:
    ``dag/compiled_dag_node.py:767``).

    Compilation pins the DAG onto mutable shared-memory channels
    (``experimental/channel.py``): every actor runs a ``__ray_dag_loop__``
    schedule reading inputs and writing outputs in place, so a per-step hop
    costs a channel write/read (microseconds) instead of a lease + RPC +
    pickle round-trip. ``execute`` writes the input channel and returns a
    ``CompiledDAGRef``; results stream out in submission order.

    Graphs that don't fit the channel model (plain function nodes, no
    InputNode) fall back to interpreted per-call submission.
    """

    def __init__(self, root: DAGNode, buffer_size_bytes: Optional[int] = None):
        from ray_tpu.experimental import channel as chan

        self._root = root
        self._chan = chan
        self._capacity = buffer_size_bytes or chan.DEFAULT_CAPACITY
        # Materialize all ClassNodes now (actor startup off the hot path).
        warm: Dict[int, Any] = {}
        for node in _walk(root):
            if isinstance(node, ClassNode):
                node._ensure_actor(warm)
        self._warm = warm
        self._lock = threading.RLock()
        self._next_seq = 0
        self._read_count = 0
        self._partial: List[Any] = []
        self._results: Dict[int, Any] = {}
        self._torn_down = False
        try:
            self._build_channels()
            self._channel_mode = True
        except _UnsupportedDAG:
            self._channel_mode = False

    # ------------------------------------------------------------- compile
    def _topo_nodes(self) -> List[DAGNode]:
        order: List[DAGNode] = []
        seen = set()

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            deps = [a for a in list(node._bound_args)
                    + list(node._bound_kwargs.values())
                    if isinstance(a, DAGNode)]
            for d in deps:
                visit(d)
            order.append(node)

        visit(self._root)
        return order

    def _build_channels(self):
        chan = self._chan
        topo = self._topo_nodes()
        inputs = [n for n in topo if isinstance(n, InputNode)]
        outputs = (list(self._root._bound_args)
                   if isinstance(self._root, MultiOutputNode)
                   else [self._root])
        compute = [n for n in topo
                   if not isinstance(n, (InputNode, MultiOutputNode,
                                         ClassNode))]
        if len(inputs) != 1 or not compute:
            raise _UnsupportedDAG("channel mode needs one InputNode")
        if not all(isinstance(n, ActorMethodNode) for n in compute):
            raise _UnsupportedDAG("channel mode pins actor methods only")
        if not all(isinstance(o, ActorMethodNode) for o in outputs):
            raise _UnsupportedDAG("outputs must be actor methods")

        # Count consumer edges per producer (driver counts for outputs).
        n_edges: Dict[int, int] = {}
        for n in compute:
            for a in list(n._bound_args) + list(n._bound_kwargs.values()):
                if isinstance(a, DAGNode):
                    n_edges[id(a)] = n_edges.get(id(a), 0) + 1
        for o in outputs:
            n_edges[id(o)] = n_edges.get(id(o), 0) + 1

        self._channels: List[Any] = []
        out_chan: Dict[int, Any] = {}
        for n in [inputs[0]] + compute:
            if id(n) not in n_edges:
                raise _UnsupportedDAG(f"dangling node {n}")
            c = chan.Channel(capacity=self._capacity,
                             n_readers=n_edges[id(n)])
            out_chan[id(n)] = c
            self._channels.append(c)

        next_idx: Dict[int, int] = {}

        def reader_for(producer: DAGNode):
            i = next_idx.get(id(producer), 0)
            next_idx[id(producer)] = i + 1
            return out_chan[id(producer)].reader(i)

        # Per-actor executable schedule in topological order (reference:
        # ExecutableTask lists, compiled_dag_node.py:161).
        def handle_of(node: ActorMethodNode):
            target = node._target
            if isinstance(target, ClassNode):
                target = target._ensure_actor(self._warm)
            return target

        per_actor: Dict[bytes, List[tuple]] = {}
        actor_handles: Dict[bytes, Any] = {}
        for n in compute:
            h = handle_of(n)
            key = h._actor_id.binary()
            arg_slots = [reader_for(a) if isinstance(a, DAGNode) else a
                         for a in n._bound_args]
            kwarg_slots = {k: (reader_for(v) if isinstance(v, DAGNode)
                               else v)
                           for k, v in n._bound_kwargs.items()}
            per_actor.setdefault(key, []).append(
                (n._method_name, arg_slots, kwarg_slots, out_chan[id(n)]))
            actor_handles[key] = h

        # Driver endpoints (readers assigned after actor edges).
        self._input_channel = out_chan[id(inputs[0])]
        self._output_readers = [reader_for(o) for o in outputs]
        self._multi_output = isinstance(self._root, MultiOutputNode)
        # Bound in-flight executions to the pipeline's holding capacity so
        # an over-eager submit blocks HERE (lock-free) instead of inside the
        # input-channel write while holding the driver lock — which would
        # deadlock, since draining results also needs that lock (reference:
        # max in-flight executions, compiled_dag_node.py). Capacity along a
        # path of d actors is d+1 channel slots + d in-execution slots; the
        # shallowest input→output path is the bottleneck.
        depth: Dict[int, int] = {id(inputs[0]): 0}
        for n in compute:  # topo order: producers already have depths
            dag_args = [a for a in list(n._bound_args)
                        + list(n._bound_kwargs.values())
                        if isinstance(a, DAGNode)]
            n_depth = 1 + min(depth.get(id(a), 0) for a in dag_args)
            depth[id(n)] = n_depth
        min_depth = min(depth.get(id(o), 1) for o in outputs)
        self._inflight_sem = threading.Semaphore(2 * min_depth + 1)

        from ray_tpu.actor import ActorMethod

        self._loop_refs = [
            ActorMethod(actor_handles[key], "__ray_dag_loop__").remote(ops)
            for key, ops in per_actor.items()]

    # ------------------------------------------------------------- execute
    def execute(self, *input_args):
        if self._torn_down:
            raise RuntimeError("CompiledDAG was torn down")
        if not self._channel_mode:
            return self._root.execute(*input_args)
        value = input_args[0] if len(input_args) == 1 else input_args
        # Block lock-free while the pipeline is full; a single-threaded
        # caller that never drains would wait forever, so surface the
        # misuse after a bounded wait (reference raises when max buffered
        # results is exceeded).
        if not self._inflight_sem.acquire(timeout=60.0):
            raise RuntimeError(
                "compiled DAG pipeline is full and no result was consumed "
                "for 60s; call get() on earlier CompiledDAGRefs to drain")
        try:
            with self._lock:
                # Write under the lock: the channel is single-writer, and
                # the seq must match the write order. The semaphore
                # guarantees a free slot, so this write cannot block.
                self._input_channel.write(value)
                seq = self._next_seq
                self._next_seq += 1
        except BaseException:
            self._inflight_sem.release()
            raise
        return CompiledDAGRef(self, seq)

    def _get_result(self, seq: int, timeout: Optional[float]):
        chan = self._chan
        with self._lock:
            if seq < self._read_count and seq not in self._results:
                raise ValueError(
                    f"CompiledDAGRef (execution #{seq}) was already "
                    f"consumed; get() may only be called once per ref")
            while seq >= self._read_count:
                # Resume partially-read ticks: a timeout mid-tick must not
                # discard values already consumed from earlier readers or
                # every later result would pair mismatched executions.
                while len(self._partial) < len(self._output_readers):
                    r = self._output_readers[len(self._partial)]
                    self._partial.append(r.read(timeout=timeout))
                vals, self._partial = self._partial, []
                self._results[self._read_count] = (
                    vals if self._multi_output else vals[0])
                self._read_count += 1
                self._inflight_sem.release()
            out = self._results.pop(seq)
        for v in (out if isinstance(out, list) else [out]):
            if isinstance(v, chan._StageError):
                raise v.exc
        return out

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        if self._channel_mode:
            # Close EVERY channel, not just the input: an actor blocked
            # writing an unread output would never observe an input-only
            # close and would spin forever in the pinned loop.
            for c in self._channels:
                c.close()
            for ref in self._loop_refs:
                try:
                    ray_tpu.get(ref, timeout=10)
                except Exception:  # noqa: BLE001
                    pass
            for c in self._channels:
                c.destroy()
        for node in _walk(self._root):
            if isinstance(node, ClassNode) and node._handle is not None:
                try:
                    ray_tpu.kill(node._handle)
                except Exception:  # noqa: BLE001
                    pass


def _walk(root: DAGNode):
    visited = set()
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        yield node
        for a in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                stack.append(a)
        if isinstance(node, ActorMethodNode) and \
                isinstance(node._target, ClassNode):
            stack.append(node._target)


__all__ = [
    "ActorMethodNode", "ClassNode", "CompiledDAG", "CompiledDAGRef",
    "DAGNode", "FunctionNode", "InputNode", "MultiOutputNode",
]
