"""Runtime context: introspection of the current job/task/actor/node.

Re-design of the reference (reference: ``python/ray/runtime_context.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private import worker as _worker


class RuntimeContext:
    @property
    def _core(self):
        return _worker.global_worker().core

    def get_job_id(self) -> str:
        return self._core.job_id.hex()

    def get_node_id(self) -> str:
        return self._core.node_id.hex()

    def get_task_id(self) -> Optional[str]:
        from ray_tpu._private.runtime.local import current_task_context

        ctx = current_task_context()
        return ctx.task_id.hex() if ctx else None

    def get_actor_id(self) -> Optional[str]:
        from ray_tpu._private.runtime.local import current_task_context

        ctx = current_task_context()
        return ctx.actor_id.hex() if ctx and ctx.actor_id else None

    def get_actor_name(self) -> Optional[str]:
        from ray_tpu._private.runtime.local import current_task_context

        ctx = current_task_context()
        if ctx is None or ctx.actor_id is None:
            return None
        state = getattr(self._core, "actor_state", None)
        return (state(ctx.actor_id) or {}).get("name") if state else None

    def get_worker_id(self) -> str:
        return getattr(self._core, "worker_id", self._core.node_id).hex()

    def get_assigned_resources(self) -> Dict[str, float]:
        getter = getattr(self._core, "assigned_resources", None)
        return getter() if getter else {}

    def get_placement_group_id(self) -> Optional[str]:
        getter = getattr(self._core, "current_placement_group_id", None)
        pg = getter() if getter else None
        return pg.hex() if pg else None

    def was_current_actor_reconstructed(self) -> bool:
        return False

    @property
    def namespace(self) -> str:
        return _worker.global_worker().namespace

    def get_runtime_env_string(self) -> str:
        return "{}"


_runtime_context = RuntimeContext()


def get_runtime_context() -> RuntimeContext:
    return _runtime_context
