"""ray_tpu.serve: model serving (reference: ``python/ray/serve``).

Condensed re-design of SURVEY.md §3.5's architecture:

* ``ServeController`` (named actor, ``serve/_private/controller.py:84``):
  holds deployment specs, reconciles replica actors (create/kill/restart on
  death), serves the routing table to handles.
* ``Replica`` actors (``replica.py:879``): host the user callable with high
  max_concurrency (async-replica analog); ``@serve.batch`` methods batch
  concurrent calls.
* ``DeploymentHandle`` (``handle.py:625``): routes each call with
  power-of-two-choices on per-replica in-flight counts
  (``replica_scheduler/pow_2_scheduler.py:813``'s local approximation).
* Autoscaling (``_private/autoscaling_policy.py``): replicas count ongoing
  requests; the controller scales toward ``total_ongoing / target`` within
  ``[min_replicas, max_replicas]``, applying upscale/downscale delays.
* Push-based routing (``_private/long_poll.py:204``): the controller
  publishes a route-change event over the GCS pubsub whenever a
  deployment's replica set changes; handles refresh on the event instead of
  polling on a TTL, and a call that lands on a dead replica refreshes and
  retries immediately.
* Data plane: an asyncio HTTP/1.1 ingress (keep-alive, chunked streaming,
  bounded-executor admission) plus a gRPC ingress over one shared router,
  and declarative YAML/REST deploys — see :mod:`ray_tpu.serve.proxy` and
  :mod:`ray_tpu.serve.config` (reference ``proxy.py:532,752``).
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu._private import events as _events

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "__serve_controller__"
ROUTES_CHANNEL = "SERVE_ROUTES"

# In-process route-event bus for the single-process (local) runtime, where
# controller and handles share the interpreter; cluster mode rides the GCS
# pubsub instead.
_LOCAL_BUS: List[Callable[[str], None]] = []


def _core():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker().core


def _publish_route_event(name: str) -> None:
    core = _core()
    if hasattr(core, "gcs"):
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        try:
            core.gcs.Publish(pb.PublishRequest(
                channel=ROUTES_CHANNEL, data=name.encode()))
            return
        except Exception:  # noqa: BLE001
            pass
    for cb in list(_LOCAL_BUS):
        try:
            cb(name)
        except Exception:  # noqa: BLE001
            pass


def _subscribe_route_events(cb: Callable[[str], None]) -> None:
    core = _core()
    if hasattr(core, "gcs"):
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        def loop():
            sub_id = f"serve-{uuid.uuid4().hex[:12]}"
            while True:
                try:
                    stream = core.gcs.Subscribe(pb.SubscribeRequest(
                        channels=[ROUTES_CHANNEL], subscriber_id=sub_id))
                    for msg in stream:
                        cb(msg.data.decode())
                except Exception:  # noqa: BLE001
                    time.sleep(0.5)

        threading.Thread(target=loop, daemon=True,
                         name="serve-routes-sub").start()
    else:
        _LOCAL_BUS.append(cb)


DEFAULT_AUTOSCALING = {
    "min_replicas": 1,
    "max_replicas": 4,
    "target_ongoing_requests": 2.0,
    "upscale_delay_s": 0.3,
    "downscale_delay_s": 2.0,
    # Engine-pressure policy (fed by the per-replica pressure fan-out):
    # desired replicas also scale on admission-queue depth per replica,
    # on paged-KV arena starvation (every engine replica with zero
    # free+reclaimable blocks), and on ingress sheds observed since the
    # last decision — replicas scale on ENGINE pressure, not just the
    # router's ongoing-count. 0 disables a signal.
    "target_queue_depth": 4.0,
    "kv_starvation_upscale": True,
    "shed_upscale": True,
    # Disaggregated-role signals (0/off by default — generic
    # deployments never pay them): prefill fleets scale on waiting
    # prompt tokens per replica; decode fleets add a replica when EVERY
    # engine's importable-block headroom (free + LRU-reclaimable)
    # drops under the floor — the next KV handoff's reservation is
    # about to fail.
    "target_prefill_queue_tokens": 0.0,
    "importable_floor": 0.0,
}


# ------------------------------------------------------------ role groups
# Disaggregated prefill/decode topology: a LOGICAL deployment name maps
# to its (prefill, decode) deployment pair. The ingress consults this to
# classify-and-split requests; everything else (autoscaler, pool
# arbiter, pressure fan-out) sees two ordinary deployments that scale
# independently. Registered in the ingress/router process (the only
# consumer) — `serve.run` the two deployments first, then declare the
# group; the YAML deploy path does both from a `role_groups:` section.
_ROLE_GROUPS: Dict[str, Dict[str, str]] = {}
_ROLE_GROUPS_LOCK = threading.Lock()


def register_role_group(name: str, *, prefill: str, decode: str) -> None:
    """Declare ``name`` as a disaggregated role group: streaming LLM
    requests to ``name`` are classified at the ingress and either split
    (prefill on ``prefill``, KV handoff, decode on ``decode``) or sent
    to ``decode`` whole (its engines run colocated admission too)."""
    if not prefill or not decode:
        raise ValueError("role group needs both a prefill and a decode "
                         "deployment name")
    with _ROLE_GROUPS_LOCK:
        _ROLE_GROUPS[name] = {"prefill": prefill, "decode": decode}


def get_role_group(name: str) -> Optional[Dict[str, str]]:
    with _ROLE_GROUPS_LOCK:
        g = _ROLE_GROUPS.get(name)
        return dict(g) if g else None


def unregister_role_group(name: str) -> bool:
    with _ROLE_GROUPS_LOCK:
        return _ROLE_GROUPS.pop(name, None) is not None


class Replica:
    """Hosts one copy of the user callable.

    Async-native (reference: Serve replicas run user code on the replica
    actor's event loop, ``serve/_private/replica.py``): ``handle_request``
    is a coroutine, so the replica actor runs on a dedicated asyncio loop
    and an async user ``__call__`` overlaps slow requests up to the
    deployment's ``max_concurrency``. Sync user code runs in a thread
    executor so it still overlaps (threaded-deployment behavior) instead
    of blocking the loop.
    """

    def __init__(self, cls_or_fn, init_args, init_kwargs, is_function: bool,
                 sync_workers: int = 8):
        import inspect
        from concurrent.futures import ThreadPoolExecutor as _TPE

        self.is_function = is_function
        if is_function:
            self.instance = cls_or_fn
        else:
            self.instance = cls_or_fn(*init_args, **(init_kwargs or {}))
        self._ongoing = 0
        self._total = 0
        self._draining = False
        self._m_lock = threading.Lock()
        self._inspect = inspect
        self._sync_pool = _TPE(max_workers=max(1, int(sync_workers)),
                               thread_name_prefix="replica-sync")

    def _admit(self) -> None:
        """Count one request in — or reject it if this replica is
        draining. The reject is a CLEAN typed error (the replica did no
        work): routers re-route it to a live replica without consuming
        the request's resume budget."""
        with self._m_lock:
            if self._draining:
                raise ray_tpu.exceptions.ReplicaDrainingError(
                    "replica is draining and no longer admits requests")
            self._ongoing += 1
            self._total += 1

    def _target(self, method: str):
        if self.is_function:
            return self.instance
        return getattr(self.instance, method or "__call__")

    async def handle_request(self, method: str, args, kwargs,
                             multiplexed_model_id: str = "",
                             request_ctx: Optional[Dict[str, Any]] = None):
        import asyncio
        import contextvars

        from ray_tpu.serve import context as serve_context
        from ray_tpu.serve import multiplex

        self._admit()
        token = multiplex._set_model_id(multiplexed_model_id)
        # The request context (request id + trace linkage) must be set
        # BEFORE copy_context() below so sync user code sees it in the
        # executor thread — same mechanism as the model id.
        rtoken = (serve_context._set_request_context(request_ctx)
                  if request_ctx is not None else None)
        try:
            target = self._target(method)
            if self._inspect.iscoroutinefunction(target):
                return await target(*args, **kwargs)
            # Sync user code: off the loop so it can't stall concurrent
            # requests. The context (multiplexed model id) rides along.
            ctx = contextvars.copy_context()
            return await asyncio.get_running_loop().run_in_executor(
                self._sync_pool, lambda: ctx.run(target, *args, **kwargs))
        finally:
            if rtoken is not None:
                serve_context._reset_request_context(rtoken)
            multiplex._reset_model_id(token)
            with self._m_lock:
                self._ongoing -= 1

    async def handle_request_streaming(self, method: str, args, kwargs,
                                       multiplexed_model_id: str = "",
                                       request_ctx: Optional[Dict[str,
                                                                  Any]] = None):
        """Streaming variant: each yield of the user method becomes one
        streamed item when called with num_returns="streaming" (reference:
        DeploymentResponseGenerator / RayServeHandle stream=True). Accepts
        sync and async generators."""
        from ray_tpu.serve import context as serve_context
        from ray_tpu.serve import multiplex

        self._admit()
        token = multiplex._set_model_id(multiplexed_model_id)
        rtoken = (serve_context._set_request_context(request_ctx)
                  if request_ctx is not None else None)
        try:
            result = self._target(method)(*args, **kwargs)
            if hasattr(result, "__aiter__"):
                async for item in result:
                    yield item
            elif hasattr(result, "__next__"):
                # Sync generator: pull each item off-loop so a slow
                # producer (time.sleep between yields) can't stall the
                # replica's other in-flight requests. The copied context
                # carries the multiplexed-model-id ContextVar into the
                # pool thread (same as the non-streaming sync path).
                import asyncio
                import contextvars

                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()
                sentinel = object()

                def _next():
                    try:
                        return ctx.run(next, result)
                    except StopIteration:
                        return sentinel

                while True:
                    item = await loop.run_in_executor(self._sync_pool, _next)
                    if item is sentinel:
                        break
                    yield item
            else:
                raise TypeError(
                    f"stream=True requires a generator; "
                    f"{method or '__call__'!r} returned "
                    f"{type(result).__name__}")
        finally:
            if rtoken is not None:
                serve_context._reset_request_context(rtoken)
            multiplex._reset_model_id(token)
            with self._m_lock:
                self._ongoing -= 1

    def metrics(self):
        """Ongoing-request count the autoscaler averages (reference:
        replica metrics pushed to the controller, autoscaling_policy.py)."""
        with self._m_lock:
            return {"ongoing": self._ongoing, "total": self._total}

    def pressure(self):
        """Pressure snapshot for the serve pressure endpoint: router
        in-flight counts plus whatever the hosted callable reports (the
        continuous-batching deployments expose queue depth / KV blocks
        free / in-flight prefill tokens through their own ``pressure()``)."""
        with self._m_lock:
            out = {"ongoing": self._ongoing, "total": self._total}
        if not self.is_function:
            probe = getattr(self.instance, "pressure", None)
            if callable(probe):
                try:
                    out.update(probe() or {})
                except Exception:  # noqa: BLE001 — monitoring must not
                    pass           # fail requests' host process
        return out

    def health(self):
        return True

    def node_id(self):
        """The node hosting this replica — the controller's key for
        preemption-notice targeting (a notice naming a node drains that
        node's replicas instead of letting them be guillotined)."""
        try:
            return ray_tpu.get_runtime_context().get_node_id()
        except Exception:  # noqa: BLE001 — no runtime context: untargetable
            return ""

    async def drain(self, deadline_s: Optional[float] = None):
        """Controller-initiated graceful drain: stop admitting (new
        requests get a clean :class:`ReplicaDrainingError` reject and
        re-route), finish in-flight requests up to ``deadline_s``
        (default ``RAY_TPU_SERVE_DRAIN_S``), then report back so the
        controller tears this replica down. Async — in-flight requests
        keep executing on this actor's loop while the drain waits."""
        import asyncio

        from ray_tpu._private import chaos

        if deadline_s is None:
            deadline_s = float(os.environ.get("RAY_TPU_SERVE_DRAIN_S",
                                              "30"))
        with self._m_lock:
            self._draining = True
            remaining = self._ongoing
        t0 = time.monotonic()
        deadline = t0 + max(float(deadline_s), 0.0)
        while remaining > 0 and time.monotonic() < deadline:
            if chaos.enabled():
                # Death-while-draining chaos site: the host dies before
                # the drain completes — in-flight streams fall back to
                # the journal's resume path. delay_drain (serve_drain
                # site) instead stretches the wait: a slow quiesce under
                # which the pool arbiter's FREEING stage must hold.
                chaos.inject("serve_replica", phase="drain")
                chaos.inject("serve_drain")
            await asyncio.sleep(0.02)
            with self._m_lock:
                remaining = self._ongoing
        return {"drained": remaining <= 0,
                "waited_s": time.monotonic() - t0,
                "remaining": remaining}


class ServeController:
    """Reconciles deployment specs → replica actors and autoscales them."""

    def __init__(self):
        self.deployments: Dict[str, Dict[str, Any]] = {}
        self.replicas: Dict[str, List[Any]] = {}
        self._route_version: Dict[str, int] = {}
        # Shared router loads: name -> (ts, [ongoing per replica]).
        self._loads_cache: Dict[str, Any] = {}
        # Pressure snapshots: name -> (ts, [per-replica dicts]).
        self._pressure_cache: Dict[str, Any] = {}
        # autoscaler intent: name -> (desired, first_seen_monotonic)
        self._scale_intent: Dict[str, Any] = {}
        # Cumulative ingress-shed count seen at the last autoscale
        # decision per deployment (the policy scales on the DELTA).
        self._shed_seen: Dict[str, float] = {}
        self._pg_cleanups: Dict[str, list] = {}
        self._replica_birth: Dict[int, float] = {}
        # Draining replicas: name -> [{replica, ref, t0, deadline,
        # cause}]. Out of the routing table (get_routes/pressure only
        # see self.replicas) but not yet torn down: each entry's ``ref``
        # is the in-flight Replica.drain() call, and _advance_drains
        # kills the replica when it resolves (drained / died) or the
        # deadline lapses.
        self._draining: Dict[str, List[Dict[str, Any]]] = {}
        self._reconcile_lock = threading.Lock()
        self._stop = False
        # Preemption notices drain a node's replicas instead of letting
        # the kill guillotine their in-flight requests (the serve twin
        # of the train plane's JIT-save guards; same pubsub channel).
        from ray_tpu.checkpoint import preempt as _preempt

        def _on_preempt(notice: Dict[str, Any]) -> None:
            # Elastic control signals (capacity hints, world-target
            # asks) ride this channel but are the trainers' to latch.
            if notice.get("kind") == "capacity" or \
                    notice.get("world_target") is not None:
                return
            try:
                self._drain_for_preemption(notice)
            except Exception:  # noqa: BLE001 — drain is best-effort
                logger.exception("preemption drain failed")

        self._preempt_cb = _preempt.register_preempt_callback(_on_preempt)
        try:
            _preempt.ensure_listener()
        except Exception:  # noqa: BLE001
            pass
        threading.Thread(target=self._reconcile_loop, daemon=True).start()

    def deploy(self, name: str, cls_or_fn, init_args, init_kwargs,
               num_replicas: int, is_function: bool,
               max_concurrency: int,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               placement_strategy: Optional[str] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None) -> bool:
        cfg = None
        if autoscaling_config is not None or num_replicas == "auto":
            cfg = dict(DEFAULT_AUTOSCALING)
            cfg.update(autoscaling_config or {})
            num_replicas = cfg["min_replicas"]
        with self._reconcile_lock:
            # The swap must not race a reconcile in flight (the loop
            # thread would write its group into an orphaned spec dict).
            prev = self.deployments.get(name) or {}
            keep_group = prev.get("placement") == placement_strategy == \
                "COMPACT" and \
                prev.get("actor_options") == dict(ray_actor_options or {})
            self.deployments[name] = {
                "cls": cls_or_fn, "args": init_args, "kwargs": init_kwargs,
                "num_replicas": num_replicas, "is_function": is_function,
                "max_concurrency": max_concurrency, "autoscaling": cfg,
                # Deployment scheduler (reference: deployment_scheduler.py
                # compact placement): COMPACT gangs replicas onto as few
                # nodes as possible via a PACK placement group; SPREAD
                # spreads them with the min-utilization policy.
                "placement": placement_strategy,
                "actor_options": dict(ray_actor_options or {}),
                # A same-shape COMPACT redeploy inherits the group (its
                # reservation would otherwise leak unreachable); any
                # placement/resource change starts clean.
                "_pg": prev.get("_pg") if keep_group else None,
            }
            if prev.get("_pg") is not None and not keep_group:
                old_pg = prev["_pg"]
                # Old gang + group are torn down: replicas would otherwise
                # keep double-charging the cluster alongside the new ones.
                for r in self.replicas.get(name, []):
                    self._replica_birth.pop(id(r), None)
                    try:
                        ray_tpu.kill(r)
                    except Exception:  # noqa: BLE001
                        pass
                self.replicas[name] = []
                try:
                    from ray_tpu.util import remove_placement_group

                    remove_placement_group(old_pg)
                except Exception:  # noqa: BLE001
                    pass
        self._reconcile_once(name)
        return True

    @staticmethod
    def _shed_total(name: str) -> float:
        """Cumulative ingress sheds for a deployment (pressure + tenant
        buckets), via the shared readback in metrics_defs. In-process
        registry: the local runtime hosts ingress and controller in one
        process; cluster deployments scale primarily on the queue/KV
        pressure signals."""
        from ray_tpu._private import metrics_defs as mdefs

        return mdefs.serve_shed_total(name)

    def _pressure_desired(self, name: str, cfg: Dict[str, Any],
                          current: int) -> tuple:
        """(desired, signal) under the pressure policy: the max over the
        ongoing-count target (reference: autoscaling_policy.py), the
        engine admission-queue target, arena starvation, and the
        ingress-shed delta — each signal reads the same per-replica
        pressure fan-out the router and dashboard already consume."""
        snaps = [s for s in self.get_replica_pressure(name)
                 if s and not s.get("unreachable")]
        ongoing = sum(float(s.get("ongoing") or 0) for s in snaps)
        desired = math.ceil(ongoing / max(cfg["target_ongoing_requests"],
                                          1e-9))
        signal = "ongoing"
        tq = float(cfg.get("target_queue_depth") or 0)
        if tq > 0:
            queue = sum(float(s.get("queue_depth") or 0) for s in snaps)
            d_q = math.ceil(queue / tq)
            if d_q > desired:
                desired, signal = d_q, "queue"
        if cfg.get("kv_starvation_upscale"):
            engines = [s for s in snaps
                       if float(s.get("kv_blocks_total") or 0) > 0]
            starved = [s for s in engines
                       if (float(s.get("kv_blocks_free") or 0)
                           + float(s.get("kv_blocks_cached") or 0)) <= 0]
            if engines and len(starved) == len(engines) and \
                    current + 1 > desired:
                # EVERY engine replica has nothing left to admit with:
                # one more replica, even when queue counters look calm.
                desired, signal = current + 1, "kv"
        tpt = float(cfg.get("target_prefill_queue_tokens") or 0)
        if tpt > 0:
            # Prefill-role fleets: waiting prompt tokens (admission
            # queue + parked handoffs) are the work unit, not request
            # count — one 4k-token prompt loads a replica like dozens
            # of short ones.
            ptoks = sum(float(s.get("prefill_queue_tokens") or 0)
                        for s in snaps)
            d_p = math.ceil(ptoks / tpt)
            if d_p > desired:
                desired, signal = d_p, "prefill_tokens"
        imp_floor = float(cfg.get("importable_floor") or 0)
        if imp_floor > 0:
            # Decode-role fleets: when EVERY engine's importable-block
            # headroom is under the floor, the next handoff's
            # reservation is about to fail — add a replica before the
            # transfer plane starts bouncing.
            engines = [s for s in snaps
                       if float(s.get("kv_blocks_total") or 0) > 0]
            low = [s for s in engines
                   if float(s.get("kv_blocks_importable") or 0)
                   < imp_floor]
            if engines and len(low) == len(engines) and \
                    current + 1 > desired:
                desired, signal = current + 1, "importable"
        if cfg.get("shed_upscale"):
            sheds = self._shed_total(name)
            last = self._shed_seen.setdefault(name, sheds)
            self._shed_seen[name] = sheds
            if sheds > last and current + 1 > desired:
                desired, signal = current + 1, "shed"
        return desired, signal

    def _autoscale_once(self, name: str):
        """Closed-loop replica scaling: desired comes from the pressure
        policy (ongoing count, engine queue depth, KV-arena starvation,
        shed rate), clamped to [min, max] and the pool arbiter's chip
        cap, applied after the respective upscale/downscale delay holds
        steadily. Scale-down always goes through the drain path
        (reconcile drains victims instead of killing)."""
        from ray_tpu._private import metrics_defs as mdefs

        spec = self.deployments.get(name)
        if spec is None or spec["autoscaling"] is None:
            return
        cfg = spec["autoscaling"]
        if not self.replicas.get(name, []):
            return
        current = spec["num_replicas"]
        desired, signal = self._pressure_desired(name, cfg, current)
        lo, hi = cfg["min_replicas"], cfg["max_replicas"]
        cap = spec.get("pool_cap")
        if cap is not None:
            # Chips leased away by the pool arbiter are a hard ceiling —
            # below min_replicas too: the arbiter's SLO guard is the
            # path back, not a tug-of-war with the reconciler.
            hi = min(hi, int(cap))
            lo = min(lo, hi)
        desired = max(lo, min(hi, desired))
        if desired == current:
            self._scale_intent.pop(name, None)
            return
        now = time.monotonic()
        intent = self._scale_intent.get(name)
        if intent is None or intent[0] != desired:
            self._scale_intent[name] = (desired, now)
            return
        delay = (cfg["upscale_delay_s"] if desired > current
                 else cfg["downscale_delay_s"])
        if now - intent[1] < delay:
            return
        with self._reconcile_lock:
            live = self.deployments.get(name)
            if live is not None:
                live["num_replicas"] = desired
        self._scale_intent.pop(name, None)
        mdefs.SERVE_AUTOSCALE_DECISIONS.inc(tags={
            "deployment": name,
            "direction": "up" if desired > current else "down",
            "signal": signal})
        # Flight-recorder root for the scale-down drains the reconcile
        # below starts (they cite this decision as their cause_event).
        scale_ev = _events.emit(
            "serve.autoscale", subject={"deployment": name},
            direction="up" if desired > current else "down",
            signal=signal, current=current, desired=desired)
        self._reconcile_once(name, cause_event=scale_ev)

    def _routes_changed(self, name: str) -> None:
        """Publish a new routing table version AND drop the controller's
        own loads/pressure caches for the deployment: they are arrays
        aligned per-index with the OLD table, and routers refetching
        after the event would otherwise be served the stale,
        index-misaligned snapshots for up to a TTL (mis-costing
        survivors / shedding on a removed replica's entry)."""
        self._loads_cache.pop(name, None)
        self._pressure_cache.pop(name, None)
        self._route_version[name] = self._route_version.get(name, 0) + 1
        _publish_route_event(name)

    DRAIN_GRACE_S = 2.0  # RPC slack past the replica's own deadline

    def _begin_drain(self, name: str, replica, cause: str,
                     cause_event: str = "") -> None:
        """Start one replica's graceful drain. The caller (under the
        reconcile lock) has already removed it from the routing table;
        this fires ``Replica.drain`` and parks the entry for
        :meth:`_advance_drains` to finish. A replica that cannot even be
        asked to drain is killed on the spot. ``cause_event`` links the
        flight-recorder record to what forced the drain (a preemption
        notice id, an autoscale decision)."""
        from ray_tpu._private import metrics_defs as mdefs

        deadline_s = float(os.environ.get("RAY_TPU_SERVE_DRAIN_S", "30"))
        replica_tag = f"{id(replica):x}"
        entry = {"replica": replica, "t0": time.monotonic(),
                 "deadline": time.monotonic() + deadline_s,
                 "cause": cause, "ref": None}
        try:
            entry["ref"] = replica.drain.remote(deadline_s)
        except Exception:  # noqa: BLE001 — undrainable: tear down now
            _events.emit("serve.drain_begin", cause=cause_event,
                         subject={"deployment": name,
                                  "replica": replica_tag},
                         drain_cause=cause, outcome="undrainable")
            try:
                ray_tpu.kill(replica)
            except Exception:  # noqa: BLE001
                pass
            return
        entry["event_id"] = _events.emit(
            "serve.drain_begin", cause=cause_event,
            subject={"deployment": name, "replica": replica_tag},
            drain_cause=cause, deadline_s=deadline_s)
        self._draining.setdefault(name, []).append(entry)
        mdefs.SERVE_REPLICA_DRAINS.inc(tags={"deployment": name,
                                             "cause": cause})

    def _advance_drains(self, name: str) -> None:
        """Finish drains whose Replica.drain resolved (drained, hit its
        deadline, or died mid-drain) — tear the replica down and record
        the drain duration by outcome. Requests still running when the
        deadline lapses are killed with the replica; their callers'
        journals resume them on a live replica (death-while-draining
        falls back to the resume path by design)."""
        # Claim the entries under the lock (a preempt callback or drain
        # RPC may append concurrently; an unlocked read-modify-write
        # here could drop their entry and leak the replica), process
        # outside it (the get below can block up to 1s), merge back.
        with self._reconcile_lock:
            entries = self._draining.pop(name, [])
        if not entries:
            return
        from ray_tpu._private import metrics_defs as mdefs

        now = time.monotonic()
        keep = []
        for e in entries:
            outcome = None
            try:
                ready, _ = ray_tpu.wait([e["ref"]], num_returns=1,
                                        timeout=0)
            except Exception:  # noqa: BLE001
                ready = []
            if ready:
                try:
                    res = ray_tpu.get(e["ref"], timeout=1)
                    outcome = ("drained" if res and res.get("drained")
                               else "deadline")
                except ray_tpu.exceptions.ActorDiedError:
                    outcome = "died"
                except Exception:  # noqa: BLE001
                    outcome = "deadline"
            elif now > e["deadline"] + self.DRAIN_GRACE_S:
                outcome = "deadline"
            if outcome is None:
                keep.append(e)
                continue
            mdefs.SERVE_DRAIN_SECONDS.observe(
                now - e["t0"], tags={"deployment": name,
                                     "outcome": outcome})
            _events.emit("serve.drain_end",
                         cause=e.get("event_id", ""),
                         subject={"deployment": name,
                                  "replica": f"{id(e['replica']):x}"},
                         outcome=outcome, drain_cause=e["cause"],
                         waited_s=now - e["t0"])
            if outcome == "died":
                mdefs.SERVE_REPLICA_DEATHS.inc(
                    tags={"deployment": name, "cause": "drain"})
            try:
                ray_tpu.kill(e["replica"])
            except Exception:  # noqa: BLE001
                pass
        if keep:
            with self._reconcile_lock:
                # EXTEND, never assign: entries appended while we were
                # processing must survive the merge.
                self._draining.setdefault(name, []).extend(keep)

    def _drain_for_preemption(self, notice: Dict[str, Any]) -> None:
        """A preemption notice for a node: drain that node's replicas
        (all replicas for an unscoped notice) instead of waiting for the
        host to kill them. The routing table drops them immediately;
        reconcile respawns replacements (checkpoint cold-start when the
        deployment was built with ``checkpoint_path``)."""
        target = str(notice.get("node", "*") or "*")
        drain_all = target in ("", "*", "all")
        # Phase 1, OUTSIDE the lock: probe replica node ids (up to ~2s
        # of remote waits — holding the reconcile lock through them
        # would freeze deploys and the very respawn work the preemption
        # deadline depends on). One shared fan-out across ALL
        # deployments (the get_replica_loads pattern).
        with self._reconcile_lock:
            snapshot = {name: list(reps)
                        for name, reps in self.replicas.items() if reps}
        hits_by_name: Dict[str, list] = {}
        if drain_all:
            hits_by_name = {n: list(reps) for n, reps in snapshot.items()}
        else:
            flat = [(name, r) for name, reps in snapshot.items()
                    for r in reps]
            refs = [r.node_id.remote() for _, r in flat]
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=2.0)
                ready_ids = {r.id().binary() for r in ready}
                for (name, r), ref in zip(flat, refs):
                    if ref.id().binary() not in ready_ids:
                        continue
                    try:
                        nid = str(ray_tpu.get(ref, timeout=0.1) or "")
                    except Exception:  # noqa: BLE001
                        continue
                    if nid and (nid == target or nid.startswith(target)):
                        hits_by_name.setdefault(name, []).append(r)
            except Exception:  # noqa: BLE001
                hits_by_name = {}
        # Phase 2, under the lock: mutate the tables — re-checking
        # membership, since reconcile may have replaced a probed
        # replica while we waited.
        with self._reconcile_lock:
            for name, hits in hits_by_name.items():
                current = list(self.replicas.get(name, []))
                hits = [r for r in hits if r in current]
                if not hits:
                    continue
                stay = [r for r in current if r not in hits]
                for r in hits:
                    self._replica_birth.pop(id(r), None)
                    # The notice id is the drain's cause: the trainer's
                    # JIT save and the arbiter's mid-handoff handling
                    # record the same id, tying all three reactions to
                    # one preemption chain.
                    self._begin_drain(
                        name, r, cause="preemption",
                        cause_event=str(notice.get("notice_id", "")))
                self.replicas[name] = stay
                self._routes_changed(name)

    def drain_replicas(self, name: str, count: int = 1,
                       cause: str = "operator") -> int:
        """Operator/test surface: drain ``count`` replicas of ``name``
        out of rotation WITHOUT shrinking the spec — reconcile respawns
        replacements (a rolling replace). Returns how many drains
        started."""
        started = 0
        with self._reconcile_lock:
            current = list(self.replicas.get(name, []))
            while current and started < count:
                victim = current.pop()
                self._replica_birth.pop(id(victim), None)
                self._begin_drain(name, victim, cause=cause)
                started += 1
            if started:
                self.replicas[name] = current
                self._routes_changed(name)
        return started

    def draining_count(self, name: str) -> int:
        return len(self._draining.get(name, []))

    # ------------------------------------------------ chip-pool surface
    def pool_set_replicas(self, name: str, target: int,
                          cap: Optional[int] = None,
                          cause: str = "pool") -> Dict[str, Any]:
        """Pool-arbiter surface: set the deployment's replica target AND
        its chip cap in one step. Shrinks go through the drain path (the
        reconcile below drains victims); the cap clamps the pressure
        autoscaler so it cannot re-grow into chips leased away
        (``cap=None`` lifts the ceiling). Returns the previous state so
        a crashed-and-restarted arbiter can re-issue this idempotently."""
        with self._reconcile_lock:
            spec = self.deployments.get(name)
            if spec is None:
                raise ValueError(f"unknown deployment {name!r}")
            prev = {"target": spec["num_replicas"],
                    "cap": spec.get("pool_cap")}
            spec["num_replicas"] = max(int(target), 0)
            spec["pool_cap"] = None if cap is None else max(int(cap), 0)
        logger.info("pool: %s replicas -> %d (cap=%s, cause=%s)",
                    name, target, cap, cause)
        self._reconcile_once(name)
        return prev

    def pool_state(self, name: str) -> Dict[str, Any]:
        """One-RPC snapshot the arbiter confirms handoff stages against:
        routed (live, routable) replicas, the spec target, drains still
        in flight, and the chip cap."""
        spec = self.deployments.get(name) or {}
        return {"routed": len(self.replicas.get(name, [])),
                "target": spec.get("num_replicas", 0),
                "draining": len(self._draining.get(name, [])),
                "cap": spec.get("pool_cap")}

    def delete(self, name: str) -> bool:
        spec = self.deployments.pop(name, None)
        for r in self.replicas.pop(name, []):
            self._replica_birth.pop(id(r), None)
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        with self._reconcile_lock:
            doomed = self._draining.pop(name, [])
        for e in doomed:
            # Deleting a deployment is an explicit teardown: draining
            # replicas go down with it.
            try:
                ray_tpu.kill(e["replica"])
            except Exception:  # noqa: BLE001
                pass
        for cleanup in self._pg_cleanups.pop(name, []):
            cleanup()
        if spec is not None and spec.get("_pg") is not None:
            try:
                from ray_tpu.util import remove_placement_group

                remove_placement_group(spec["_pg"])
            except Exception:  # noqa: BLE001
                pass
        return True

    def get_replicas(self, name: str):
        return list(self.replicas.get(name, []))

    def get_routes(self, name: str):
        """(version, replicas) — versioned routing table (long-poll analog)."""
        return self._route_version.get(name, 0), \
            list(self.replicas.get(name, []))

    LOADS_TTL_S = 0.4

    def get_replica_loads(self, name: str):
        """Per-replica ongoing-request counts, aligned with get_routes
        order and TTL-cached controller-side (reference: the pow-2
        router's replica queue-length probes,
        ``replica_scheduler/pow_2_scheduler.py:813`` — centralized here so
        N ingress processes share ONE probe stream instead of N)."""
        now = time.monotonic()
        cached = self._loads_cache.get(name)
        if cached is not None and now - cached[0] < self.LOADS_TTL_S:
            return cached[1]
        replicas = list(self.replicas.get(name, []))
        refs = [r.metrics.remote() for r in replicas]
        # One SHARED deadline for the whole probe fan-out: serial
        # per-replica 1s timeouts made a deployment with several dying
        # replicas stall the controller (and every router waiting on it)
        # for N seconds per refresh.
        loads = [1 << 20] * len(refs)  # dying replica: avoid it
        try:
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=1.0)
            ready_ids = {r.id().binary() for r in ready}
            for i, ref in enumerate(refs):
                if ref.id().binary() not in ready_ids:
                    continue
                try:
                    loads[i] = ray_tpu.get(ref, timeout=0.1)["ongoing"]
                except Exception:  # noqa: BLE001 — replica died mid-probe
                    pass
        except Exception:  # noqa: BLE001 — wait itself failed
            pass
        self._loads_cache[name] = (now, loads)
        return loads

    PRESSURE_TTL_S = 0.5

    def get_replica_pressure(self, name: str):
        """Per-replica pressure snapshots (queue depth, KV blocks free,
        in-flight prefill tokens from engine-backed replicas; router
        in-flight counts from every replica), aligned with get_routes
        order and TTL-cached — the prefix/KV-pressure router and the
        dashboard pressure endpoint both read this."""
        now = time.monotonic()
        cached = self._pressure_cache.get(name)
        if cached is not None and now - cached[0] < self.PRESSURE_TTL_S:
            return cached[1]
        replicas = list(self.replicas.get(name, []))
        refs = [r.pressure.remote() for r in replicas]
        # Shared deadline across the fan-out (same rationale as
        # get_replica_loads: dying replicas must not serialize stalls).
        out = [{"replica": i, "unreachable": True}
               for i in range(len(refs))]
        try:
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=1.0)
            ready_ids = {r.id().binary() for r in ready}
            for i, ref in enumerate(refs):
                if ref.id().binary() not in ready_ids:
                    continue
                try:
                    snap = ray_tpu.get(ref, timeout=0.1)
                    out[i] = {"replica": i, **(snap or {})}
                except Exception:  # noqa: BLE001 — died mid-probe
                    pass
        except Exception:  # noqa: BLE001 — wait itself failed
            pass
        self._pressure_cache[name] = (now, out)
        return out

    def get_pressure(self):
        """Pressure for every deployment: {name: [per-replica dicts]}."""
        return {name: self.get_replica_pressure(name)
                for name in list(self.deployments)}

    def _publish_pressure(self) -> None:
        """Mirror the pressure snapshot into the GCS KV (``__serve__`` /
        ``pressure``) so the dashboard — which talks to the GCS, not to
        actors — can serve ``/api/v1/serve/pressure`` without a runtime."""
        core = _core()
        if not hasattr(core, "gcs") or not self.deployments:
            return
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        pressure = self.get_pressure()
        body = json.dumps(pressure, sort_keys=True)
        now = time.monotonic()
        last_body, last_ts = getattr(self, "_pressure_published",
                                     (None, 0.0))
        # Unchanged data still republishes every few seconds so the
        # snapshot's ts stays a usable controller-liveness signal, but
        # an idle cluster doesn't churn the GCS KV (and its WAL) at the
        # reconcile cadence.
        if body == last_body and now - last_ts < 5.0:
            return
        self._pressure_published = (body, now)
        snap = {"ts": time.time(), "deployments": pressure}
        core.gcs.KvPut(pb.KvRequest(
            ns="__serve__", key="pressure",
            value=json.dumps(snap).encode(), overwrite=True))

    def list_deployments(self):
        return {name: {"num_replicas": spec["num_replicas"]}
                for name, spec in self.deployments.items()}

    def _reconcile_once(self, name: str, cause_event: str = ""):
        # Slow placement-group creation happens OUTSIDE the lock (a 30s
        # wait under it would freeze every deployment's maintenance);
        # the lock then only covers fast state transitions.
        self._maybe_prepare_compact_group(name)
        # One reconcile at a time: the deploy RPC thread and the loop
        # thread would otherwise race group creation / replica lists
        # (last-write-wins leaks the loser's group and replicas).
        with self._reconcile_lock:
            self._reconcile_locked(name, cause_event=cause_event)

    def _compact_needs_grow(self, spec) -> bool:
        pg = spec.get("_pg")
        if time.monotonic() < spec.get("_pg_backoff", 0.0):
            return False
        if pg is None:
            return True
        if len(pg.bundle_specs) < spec["num_replicas"]:
            return True
        # Bundle SHAPE changes (bigger replicas) need a regrow too — the
        # old bundles could never admit the new demand.
        want = self._replica_bundle(spec.get("actor_options"))
        return spec.get("_pg_bundle") != want

    def _maybe_prepare_compact_group(self, name: str) -> None:
        from ray_tpu.util import placement_group, remove_placement_group

        with self._reconcile_lock:
            spec = self.deployments.get(name)
            if spec is None or spec.get("placement") != "COMPACT" or \
                    not self._compact_needs_grow(spec):
                return
            per_replica = self._replica_bundle(spec.get("actor_options"))
            want_replicas = spec["num_replicas"]
        new_pg = placement_group([dict(per_replica)] * want_replicas,
                                 strategy="PACK")
        placed = new_pg.wait(30)
        with self._reconcile_lock:
            spec = self.deployments.get(name)
            still_needed = (
                spec is not None and spec.get("placement") == "COMPACT"
                and self._compact_needs_grow(spec)
                and spec["num_replicas"] <= want_replicas
                and self._replica_bundle(
                    spec.get("actor_options")) == per_replica)
            if not placed or not still_needed:
                try:
                    remove_placement_group(new_pg)
                except Exception:  # noqa: BLE001
                    pass
                if spec is not None and not placed:
                    # Infeasible now: keep serving on the old group (if
                    # any) and retry later instead of thrashing.
                    spec["_pg_backoff"] = time.monotonic() + 30.0
                return
            old = spec.get("_pg")
            if old is not None:
                spec["_migrate"] = True

                def _cleanup(old=old):
                    try:
                        remove_placement_group(old)
                    except Exception:  # noqa: BLE001
                        pass

                self._pg_cleanups.setdefault(name, []).append(_cleanup)
            spec["_pg"] = new_pg
            spec["_pg_bundle"] = per_replica

    def _reconcile_locked(self, name: str, cause_event: str = ""):
        spec = self.deployments.get(name)
        if spec is None:
            return
        replica_cls = ray_tpu.remote(Replica)
        current = self.replicas.setdefault(name, [])
        # Remove dead replicas (probe with a cheap health call) — but a
        # replica still STARTING (worker spawn + placement-group bundle
        # admission can take many seconds) must not be declared dead by a
        # 2s probe, or the reconciler churns forever: each dropped-but-
        # actually-starting replica still holds its bundle, so every
        # replacement starves on pg-wait.
        now = time.monotonic()
        live = []
        for r in current:
            try:
                ray_tpu.get(r.health.remote(), timeout=2)
                live.append(r)
                self._replica_birth.pop(id(r), None)  # confirmed up
            except ray_tpu.exceptions.ActorDiedError:
                # Confirmed dead: replace immediately (no grace).
                self._replica_birth.pop(id(r), None)
                from ray_tpu._private import metrics_defs as mdefs

                mdefs.SERVE_REPLICA_DEATHS.inc(
                    tags={"deployment": name, "cause": "died"})
            except Exception:  # noqa: BLE001 — timeout: starting OR dead
                birth = self._replica_birth.get(id(r))
                if birth is not None and \
                        now - birth < self.REPLICA_STARTUP_GRACE_S:
                    live.append(r)  # still starting: keep, don't churn
                else:
                    self._replica_birth.pop(id(r), None)
        current = live
        opts: Dict[str, Any] = dict(spec.get("actor_options") or {})
        opts["max_concurrency"] = spec["max_concurrency"]
        placement = spec.get("placement")
        if placement == "COMPACT":
            strategy, regrown = self._compact_group_strategy(name, spec)
            if strategy is None:
                # No feasible group yet: keep whatever runs (but still
                # push routing if the live set shrank), retry later.
                changed = [id(r) for r in current] != \
                    [id(r) for r in self.replicas.get(name, [])]
                self.replicas[name] = current
                if changed:
                    self._routes_changed(name)
                return
            opts["scheduling_strategy"] = strategy
            if regrown:
                # Migrate: the whole gang restarts inside the new group so
                # compactness holds for ALL replicas, then the old group's
                # reservation is released (even when no replica was live —
                # a dead gang's old group must not hold reservations).
                for r in current:
                    try:
                        ray_tpu.kill(r)
                    except Exception:  # noqa: BLE001
                        pass
                    self._replica_birth.pop(id(r), None)
                current = []
                for cleanup in self._pg_cleanups.pop(name, []):
                    cleanup()
        elif placement == "SPREAD":
            opts["scheduling_strategy"] = "SPREAD"
        while len(current) < spec["num_replicas"]:
            replica = replica_cls.options(**opts).remote(
                spec["cls"], spec["args"], spec["kwargs"],
                spec["is_function"],
                sync_workers=spec["max_concurrency"])
            self._replica_birth[id(replica)] = time.monotonic()
            current.append(replica)
        while len(current) > spec["num_replicas"]:
            # Scale-down DRAINS the victim instead of killing it: it
            # leaves the routing table now (the publish below), stops
            # admitting, finishes its in-flight requests up to
            # RAY_TPU_SERVE_DRAIN_S, and _advance_drains tears it down.
            victim = current.pop()
            self._replica_birth.pop(id(victim), None)
            self._begin_drain(name, victim, cause="scale_down",
                              cause_event=cause_event)
        changed = [id(r) for r in current] != \
            [id(r) for r in self.replicas.get(name, [])]
        self.replicas[name] = current
        if changed:
            # Push the new routing table to every handle (reference:
            # LongPollHost notify, long_poll.py:204).
            self._routes_changed(name)

    REPLICA_STARTUP_GRACE_S = 60.0

    @staticmethod
    def _replica_bundle(actor_options: Dict[str, Any]) -> Dict[str, float]:
        """The full resource demand of one replica (TPU serving is the
        flagship case — CPU-only bundles could never admit it)."""
        opts = actor_options or {}
        bundle: Dict[str, float] = {"CPU": float(
            opts.get("num_cpus", 1) or 1)}
        if opts.get("num_gpus"):
            bundle["GPU"] = float(opts["num_gpus"])
        if opts.get("num_tpus"):
            bundle["TPU"] = float(opts["num_tpus"])
        if opts.get("memory"):
            bundle["memory"] = float(opts["memory"])
        for k, v in (opts.get("resources") or {}).items():
            bundle[k] = float(v)
        return bundle

    def _compact_group_strategy(self, name: str, spec):
        """Hand back the deployment's group strategy (the group itself is
        prepared outside the lock by _maybe_prepare_compact_group); the
        regrown flag is a one-shot migration marker."""
        from ray_tpu.util import PlacementGroupSchedulingStrategy

        pg = spec.get("_pg")
        if pg is None:
            return None, False  # nowhere to place yet; retry next tick
        return PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=-1), \
            spec.pop("_migrate", False)

    def _reconcile_loop(self):
        from ray_tpu._private import worker as worker_mod

        while not self._stop:
            time.sleep(0.5)
            if worker_mod.global_worker_or_none() is None:
                # The hosting runtime is gone (ray_tpu.shutdown() with
                # this controller's stop RPC lost/raced): this thread is
                # orphaned. Exit instead of letting the maintenance work
                # below lazily AUTO-INITIALIZE a fresh runtime through
                # global_worker() — a zombie controller quietly owning a
                # new runtime is far worse than a missed tick.
                return
            for name in list(self.deployments):
                try:
                    self._autoscale_once(name)
                    self._reconcile_once(name)
                except Exception:  # noqa: BLE001
                    pass
            # Advance drains for every deployment with one in flight —
            # including names no longer in the spec map (a redeploy
            # mid-drain must not leak the old replica).
            for name in list(self._draining):
                try:
                    self._advance_drains(name)
                except Exception:  # noqa: BLE001
                    pass
            try:
                self._publish_pressure()
            except Exception:  # noqa: BLE001
                pass

    def shutdown(self):
        self._stop = True
        try:
            from ray_tpu.checkpoint import preempt as _preempt

            _preempt.unregister_preempt_callback(self._preempt_cb)
        except Exception:  # noqa: BLE001
            pass
        for name in list(self.deployments):
            self.delete(name)
        with self._reconcile_lock:
            leftovers = [e for entries in self._draining.values()
                         for e in entries]
            self._draining.clear()
        for e in leftovers:
            try:
                ray_tpu.kill(e["replica"])
            except Exception:  # noqa: BLE001
                pass


class DeploymentResponse:
    """Future-like response (reference: ``DeploymentResponse``)."""

    def __init__(self, ref, handle: Optional["DeploymentHandle"] = None,
                 call: Optional[tuple] = None, replica: Any = None):
        self._ref = ref
        self._handle = handle
        self._call = call
        self._replica = replica
        # Minted lazily at the FIRST retry: the clean unary path does no
        # per-request id work (with tracing off it must stay free), but a
        # re-routed/resubmitted request needs a stable subject key so its
        # flight-recorder resume events chain under one request id.
        self._request_id = ""

    def _note_flight_resume(self, mode: str, replica=None) -> None:
        name = self._handle._name
        if not self._request_id:
            self._request_id = uuid.uuid4().hex[:16]
        # Best-effort cause inference (in-process rings only). Prefer
        # THE rejecting replica's own drain record: a sibling drain (a
        # scale-down racing a preemption) can be newer but causally
        # unrelated — deployment-newest would misattribute the resume.
        # Fallbacks: the newest drain for the deployment, then the
        # newest injection/drain anywhere (the trigger observed an
        # effect — a reject, a dead replica — without its event id).
        cause = ""
        if replica is not None:
            cause = _events.latest_event_id(
                ["serve.drain_begin"],
                subject={"deployment": name,
                         "replica": f"{id(replica):x}"})
        cause = cause or _events.latest_event_id(
            ["serve.drain_begin"], subject={"deployment": name}) or \
            _events.latest_event_id(["serve.drain_begin", "chaos.inject"])
        _events.emit("serve.resume", cause=cause,
                     subject={"deployment": name,
                              "request_id": self._request_id},
                     mode=mode)

    def result(self, timeout_s: Optional[float] = 60.0):
        from ray_tpu.serve import recovery

        ref, replica = self._ref, self._replica
        resumes = 0
        drain_rejects = 0
        while True:
            try:
                out = ray_tpu.get(ref, timeout=timeout_s)
                if resumes and self._handle is not None:
                    # The call completed only thanks to >=1 death
                    # retry: tagged so the outcome counter separates
                    # clean finishes from recovered ones.
                    recovery.note_unary_resumed(self._handle._name,
                                                self._handle._model_id)
                return out
            except ray_tpu.exceptions.ReplicaDrainingError:
                # Clean reject — the draining replica did no work, so
                # the re-route is free (no resume budget). Bounded by
                # the shared cap via the eviction below.
                if self._handle is None or self._call is None or \
                        drain_rejects >= recovery.DRAIN_REJECT_CAP:
                    raise
                drain_rejects += 1
                recovery.note_unary_retry(self._handle._name,
                                          "drain_reject")
                self._note_flight_resume("drain_reject", replica)
                self._handle._evict(replica)
                args, kwargs = self._call
                retry = self._handle.remote(*args, **kwargs)
                ref, replica = retry._ref, retry._replica
            except ray_tpu.exceptions.ActorDiedError as e:
                # The chosen replica died mid-flight. A unary call's
                # journal is its immutable (args, kwargs) submission
                # plus the fact that ZERO response bytes were delivered
                # — resubmission cannot double-deliver, so the retry is
                # safe; it is still budgeted (RAY_TPU_SERVE_MAX_RESUMES,
                # not a blind fixed cap) and tagged, and exhaustion is a
                # typed terminal error (reference: router retries on
                # ActorDiedError with an updated replica set).
                if self._handle is None or self._call is None:
                    raise
                if resumes >= recovery.max_resumes():
                    recovery.note_unary_exhausted(self._handle._name,
                                                  self._handle._model_id)
                    raise recovery.exhausted_error(
                        self._handle._name, resumes) from e
                resumes += 1
                recovery.note_unary_retry(self._handle._name, "resubmit")
                self._note_flight_resume("resubmit", replica)
                self._handle._evict(replica)
                args, kwargs = self._call
                retry = self._handle.remote(*args, **kwargs)
                ref, replica = retry._ref, retry._replica

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Iterates the values of a streaming deployment call as the replica
    yields them (reference: ``DeploymentResponseGenerator`` — handle
    ``stream=True``). Wraps the core ObjectRefGenerator.
    ``per_item_timeout_s`` bounds each item (None = wait indefinitely;
    task failure still surfaces through the stream's stored error).
    Carries the serving ``_replica`` so the recovery plane
    (serve/recovery.py) can evict it from the routing table when the
    stream dies mid-flight."""

    def __init__(self, obj_ref_gen, per_item_timeout_s=None,
                 replica: Any = None):
        self._gen = obj_ref_gen
        self._timeout = per_item_timeout_s
        self._replica = replica

    def __iter__(self):
        return self

    def __next__(self):
        ref = (next(self._gen) if self._timeout is None
               else self._gen._next_internal(self._timeout))
        return ray_tpu.get(ref, timeout=self._timeout)


# Process-wide in-flight request counts per deployment: the queue-depth
# gauge must aggregate across every handle to a deployment (independent
# get_handle() calls have separate router states, and a per-handle sum
# would overwrite the series last-writer-wins).
_QUEUE_DEPTH: Dict[str, int] = {}
_QUEUE_DEPTH_LOCK = threading.Lock()


def _queue_depth_delta(deployment: str, delta: int) -> int:
    with _QUEUE_DEPTH_LOCK:
        depth = max(_QUEUE_DEPTH.get(deployment, 0) + delta, 0)
        _QUEUE_DEPTH[deployment] = depth
    return depth


class _RouterState:
    """Routing table + subscription shared by a handle and its clones."""

    def __init__(self):
        self.replicas: List[Any] = []
        self.dirty = True
        self.inflight: Dict[int, int] = {}
        self.lock = threading.Lock()
        self.subscribed = False
        # Cluster-wide per-replica load baseline from the controller
        # (other callers' traffic); local inflight rides on top.
        self.shared_loads: List[int] = []
        self.loads_ts = 0.0
        # Controller-published per-replica PRESSURE snapshots (engine
        # queue depth, KV blocks free/cached, in-flight prefill tokens),
        # TTL-cached per router: the prefix-affinity policy and the
        # ingress admission gate read the cached copy instead of paying
        # the controller's poll per request.
        self.shared_pressure: List[Dict[str, Any]] = []
        self.pressure_ts = 0.0


def _affinity_candidates(prefix_key: str, n: int) -> List[int]:
    """Rendezvous (highest-random-weight) hashing of a prefix
    fingerprint over the replica set: a stable per-key preference order
    that barely reshuffles when the replica count changes. The top TWO
    candidates are the key's home and spill replicas — a hot prefix
    concentrates on at most two KV caches instead of melting one.
    blake2b, not crc32: CRC is affine, so keys differing in a suffix
    byte order the replicas identically and every home collapses onto
    one replica."""
    import hashlib

    def weight(i: int) -> bytes:
        return hashlib.blake2b(f"{prefix_key}:{i}".encode(),
                               digest_size=8).digest()

    order = sorted(range(n), key=weight, reverse=True)
    return order[:2] if n >= 2 else order


def _pressure_cost(snap: Optional[Dict[str, Any]], local_inflight: int,
                   hot: float) -> float:
    """Congestion score for one replica: router in-flight + engine queue
    depth, plus a hot-sized penalty when the paged-KV arena has nothing
    left to admit with (free or reclaimable) — an arena-starved replica
    is as bad as a deep queue even when its router counters look calm.
    Unreachable/missing snapshots fall back to the local view only."""
    cost = float(local_inflight)
    if not snap or snap.get("unreachable"):
        return cost
    cost += float(snap.get("queue_depth") or 0)
    cost += float(snap.get("ongoing") or 0)
    total = snap.get("kv_blocks_total") or 0
    if total:
        avail = ((snap.get("kv_blocks_free") or 0)
                 + (snap.get("kv_blocks_cached") or 0))
        if avail <= 0:
            cost += hot
    return cost


def _affinity_pick(prefix_key: str, n: int,
                   pressure: List[Dict[str, Any]],
                   inflight: Dict[int, int],
                   hot: Optional[float] = None) -> tuple:
    """Choose a replica for a prefix-keyed request: stay on the key's
    rendezvous home while it is healthy (below the ``hot`` congestion
    threshold, or no worse than the spill candidate), else spill to the
    second rendezvous choice. Returns ``(index, decision)`` with
    decision in {"affinity", "overflow"}."""
    if hot is None:
        hot = float(os.environ.get("RAY_TPU_AFFINITY_HOT_COST", "8"))
    cands = _affinity_candidates(prefix_key, n)
    if len(cands) == 1:
        return cands[0], "affinity"
    c0, c1 = cands

    def cost(i):
        return _pressure_cost(pressure[i] if i < len(pressure) else None,
                              inflight.get(i, 0), hot)

    if cost(c0) < hot or cost(c0) <= cost(c1):
        return c0, "affinity"
    return c1, "overflow"


class DeploymentHandle:
    """Routes calls to replicas. The routing table is *pushed*: a subscriber
    registered on first use refreshes it when the controller publishes a
    route-change event (reference: long-poll updates, ``long_poll.py:204``)
    — no per-call TTL polling. A call that raced a replica death refreshes
    immediately and retries on a live replica."""

    def __init__(self, deployment_name: str, method_name: Optional[str] = None,
                 _router: Optional["_RouterState"] = None,
                 _stream: bool = False, _model_id: str = "",
                 _request_ctx: Optional[Dict[str, Any]] = None,
                 _prefix_key: str = ""):
        self._name = deployment_name
        self._method = method_name
        self._stream = _stream
        self._model_id = _model_id
        # Prefix fingerprint (hash of the first block-aligned prompt
        # chunks, minted at the ingress): routes the call to the replica
        # most likely to hold the prefix in its radix KV cache, tempered
        # by replica pressure. "" = no affinity (pow-2 balancing).
        self._prefix_key = _prefix_key
        # Per-call request context (request id + trace linkage, minted
        # at the ingress): ships to the replica so engine lifecycle
        # spans connect to the caller's trace. None = mint on demand
        # when tracing is enabled.
        self._request_ctx = _request_ctx
        # Router state (replica table, in-flight counts, subscription) is
        # SHARED across options()/method clones: one subscription per
        # logical handle, not per call.
        self._router = _router or _RouterState()

    def __reduce__(self):
        # Handles ship inside composed deployments' init args (reference:
        # build_app injects handles for nested bound deployments); router
        # state (locks, subscriptions, counts) is rebuilt per process,
        # call options (stream/model-id) survive the trip.
        return (_rebuild_handle,
                (self._name, self._method, self._stream, self._model_id))

    def options(self, method_name: Optional[str] = None, *,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                request_context: Optional[Dict[str, Any]] = None,
                prefix_key: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._name,
            method_name if method_name is not None else self._method,
            _router=self._router,
            _stream=self._stream if stream is None else stream,
            _model_id=(self._model_id if multiplexed_model_id is None
                       else multiplexed_model_id),
            _request_ctx=(self._request_ctx if request_context is None
                          else request_context),
            _prefix_key=(self._prefix_key if prefix_key is None
                         else prefix_key))

    @property
    def _replicas(self):
        return self._router.replicas

    @property
    def _lock(self):
        return self._router.lock

    @property
    def _inflight(self):
        return self._router.inflight

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _HandleMethod(self, name)

    def _ensure_subscribed(self):
        st = self._router
        if st.subscribed:
            return
        st.subscribed = True

        def on_event(name: str):
            if name == self._name:
                with st.lock:
                    st.dirty = True
                    # The replica set changed (death, drain, scale):
                    # per-index load/pressure snapshots are aligned with
                    # the OLD table — invalidate them so the next read
                    # refetches instead of mis-costing shifted indices
                    # (or shedding on a drained replica's stale entry).
                    st.loads_ts = 0.0
                    st.pressure_ts = 0.0
                    st.shared_loads = []
                    st.shared_pressure = []

        try:
            _subscribe_route_events(on_event)
        except Exception:  # noqa: BLE001
            pass

    def _refresh(self, force: bool = False):
        self._ensure_subscribed()
        st = self._router
        if not force and not st.dirty and st.replicas:
            return
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        _, replicas = ray_tpu.get(
            controller.get_routes.remote(self._name), timeout=30)
        with st.lock:
            changed = [id(r) for r in replicas] != \
                [id(r) for r in st.replicas]
            st.replicas = replicas
            st.dirty = False
            st.inflight = {}
            if changed:
                # New table: index-aligned caches are stale (see the
                # route-event callback above).
                st.loads_ts = 0.0
                st.pressure_ts = 0.0
                st.shared_loads = []
                st.shared_pressure = []

    def _evict(self, replica) -> None:
        """Drop a replica observed dead or draining; refreshed tables
        re-add the live set (reference: router removes failed replicas
        eagerly)."""
        st = self._router
        with st.lock:
            st.replicas = [r for r in st.replicas if r is not replica]
            st.inflight = {}
            st.dirty = not st.replicas
            # Its load/pressure entries must not cost the survivors
            # (indices shifted) or feed the admission gate.
            st.loads_ts = 0.0
            st.pressure_ts = 0.0
            st.shared_loads = []
            st.shared_pressure = []

    def _choose(self, model_id: str = "", prefix_key: str = ""):
        """Power-of-two-choices over in-flight counts; multiplexed calls
        instead hash the model id over the replica set so one model's
        requests keep hitting the replica whose LRU already holds it
        (reference: model-locality routing in serve/_private/multiplex).
        Prefix-keyed calls route by rendezvous-hashed PREFIX AFFINITY
        tempered by replica pressure: the request lands on the replica
        most likely to hold its prompt prefix in the radix KV cache,
        unless that replica is congested — then it spills to the key's
        second rendezvous choice so a hot prefix cannot melt one
        replica."""
        from ray_tpu._private import metrics_defs as mdefs

        self._refresh()
        if not self._replicas:
            # A fresh deployment may still be starting replicas.
            deadline = time.monotonic() + 10.0
            while not self._replicas and time.monotonic() < deadline:
                time.sleep(0.05)
                self._refresh(force=True)
        if not self._replicas:
            raise RuntimeError(f"deployment {self._name!r} has no replicas")
        shared: List[int] = []
        pressure: List[Dict[str, Any]] = []
        if not model_id and len(self._replicas) > 1:
            if prefix_key:
                pressure = self._fetch_shared_pressure()
            else:
                shared = self._fetch_shared_loads()
        with self._lock:
            if model_id:
                import zlib

                idx = zlib.crc32(model_id.encode()) % len(self._replicas)
            elif len(self._replicas) == 1:
                idx = 0
            elif prefix_key:
                idx, decision = _affinity_pick(
                    prefix_key, len(self._replicas), pressure,
                    self._inflight)
                mdefs.SERVE_ROUTER_AFFINITY.inc(
                    tags={"deployment": self._name, "decision": decision})
            else:
                # Pow-2 over shared (cluster-wide) + local in-flight: N
                # independent ingress processes see each other's load
                # through the controller baseline instead of each assuming
                # idle replicas (reference: pow_2_scheduler.py:813).
                loads = shared if len(shared) == len(self._replicas) \
                    else None
                a, b = random.sample(range(len(self._replicas)), 2)

                def cost(i):
                    return (loads[i] if loads else 0) + \
                        self._inflight.get(i, 0)

                idx = a if cost(a) <= cost(b) else b
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
        return idx, self._replicas[idx]

    LOADS_TTL_S = 0.5

    def _fetch_shared_loads(self) -> List[int]:
        """Controller-published per-replica queue depth, TTL-cached per
        router (one fetch per 0.5s under load, amortized over calls)."""
        st = self._router
        now = time.monotonic()
        if now - st.loads_ts < self.LOADS_TTL_S:
            return st.shared_loads
        st.loads_ts = now  # claim the slot first: no thundering herd
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            loads = list(ray_tpu.get(
                controller.get_replica_loads.remote(self._name), timeout=5))
        except Exception:  # noqa: BLE001 — fall back to local-only view
            loads = []
        with st.lock:
            st.shared_loads = loads
        return loads

    PRESSURE_TTL_S = 0.5

    def _fetch_shared_pressure(self) -> List[Dict[str, Any]]:
        """Per-replica pressure snapshots (engine queue depth, KV blocks
        free/cached, in-flight prefill tokens), TTL-cached per router —
        the freshness path: routing and ingress admission read the
        CACHED copy; only one call per TTL pays the controller round
        trip (which itself serves from its own 0.5s probe cache), so
        per-request cost is a clock read and a dict lookup. Subscribes
        to route events so a replica removal (death/drain) invalidates
        the cache even on gate-only paths that never route."""
        from ray_tpu._private import chaos

        self._ensure_subscribed()
        st = self._router
        now = time.monotonic()
        if now - st.pressure_ts < self.PRESSURE_TTL_S:
            return st.shared_pressure
        if chaos.enabled():
            # Dropped/stale pressure fetch: keep serving whatever the
            # cache holds (possibly nothing) without refreshing — the
            # admission gate and affinity policy must stay safe on
            # stale data.
            d = chaos.inject("serve_pressure", deployment=self._name)
            if d and d.get("drop"):
                return st.shared_pressure
        st.pressure_ts = now  # claim first: no thundering herd
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            snaps = list(ray_tpu.get(
                controller.get_replica_pressure.remote(self._name),
                timeout=5))
        except Exception:  # noqa: BLE001 — no controller: empty view
            snaps = []
        with st.lock:
            st.shared_pressure = snaps
        return snaps

    def _observe_done(self, start: float) -> None:
        from ray_tpu._private import metrics_defs as mdefs

        mdefs.SERVE_LATENCY.observe(time.monotonic() - start,
                                    tags={"deployment": self._name})
        mdefs.SERVE_QUEUE_DEPTH.set(_queue_depth_delta(self._name, -1),
                                    tags={"deployment": self._name})

    def remote(self, *args, **kwargs):
        from ray_tpu.util import tracing

        if not tracing.enabled():
            # Hot path with tracing off: one env check, no context work.
            return self._remote_impl(args, kwargs, self._request_ctx)
        rctx = self._request_ctx
        if rctx is None:
            # Direct handle call (no ingress): mint the request identity
            # here, continuing the caller's trace when one is active.
            cur = tracing.current()
            rctx = {"request_id": tracing.gen_id(),
                    "trace_id": cur[0] if cur else tracing.gen_id(),
                    "parent_span_id": cur[1] if cur else "",
                    "deployment": self._name, "tenant": self._model_id}
        parent = rctx.get("parent_span_id", "")
        # Pre-allocate the route span id so the engine's lifecycle spans
        # (emitted from the replica long after this returns) can parent
        # to it; the span itself closes when dispatch completes.
        route_span = tracing.gen_id()
        rctx = {**rctx, "parent_span_id": route_span}
        with tracing.explicit_span(
                "serve.route", trace_id=rctx.get("trace_id", ""),
                span_id=route_span, parent_span_id=parent, kind="route",
                request_id=rctx.get("request_id", ""),
                deployment=self._name):
            return self._remote_impl(args, kwargs, rctx)

    def _remote_impl(self, args, kwargs, request_ctx):
        from ray_tpu._private import metrics_defs as mdefs

        idx, replica = self._choose(self._model_id, self._prefix_key)
        mdefs.SERVE_REQUESTS.inc(tags={"deployment": self._name})
        mdefs.SERVE_QUEUE_DEPTH.set(_queue_depth_delta(self._name, +1),
                                    tags={"deployment": self._name})
        start = time.monotonic()
        if self._stream:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming").remote(
                self._method, args, kwargs, self._model_id, request_ctx)

            def _sdone(_fut):
                with self._lock:
                    self._inflight[idx] = max(
                        self._inflight.get(idx, 1) - 1, 0)
                self._observe_done(start)

            try:
                gen.completed().future().add_done_callback(_sdone)
            except Exception:  # noqa: BLE001
                _sdone(None)
            return DeploymentResponseGenerator(gen, replica=replica)
        ref = replica.handle_request.remote(self._method, args, kwargs,
                                            self._model_id, request_ctx)

        def _done(_fut):
            with self._lock:
                self._inflight[idx] = max(self._inflight.get(idx, 1) - 1, 0)
            self._observe_done(start)

        try:
            ref.future().add_done_callback(_done)
        except Exception:  # noqa: BLE001
            from ray_tpu._private import metrics_defs as mdefs

            with self._lock:
                self._inflight[idx] = max(self._inflight.get(idx, 1) - 1, 0)
            # Balance the queue-depth gauge: the done callback that would
            # normally decrement it will never fire.
            mdefs.SERVE_QUEUE_DEPTH.set(_queue_depth_delta(self._name, -1),
                                        tags={"deployment": self._name})
        return DeploymentResponse(ref, handle=self, call=(args, kwargs),
                                  replica=replica)


def _rebuild_handle(name, method, stream, model_id) -> "DeploymentHandle":
    return DeploymentHandle(name, method, _stream=stream,
                            _model_id=model_id)


class _HandleMethod:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.options(self._method).remote(*args, **kwargs)


class Application:
    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 max_ongoing_requests: int = 100,
                 ray_actor_options: Optional[Dict] = None,
                 autoscaling_config: Optional[Dict[str, Any]] = None,
                 placement_strategy: Optional[str] = None,
                 init_kwargs: Optional[Dict[str, Any]] = None):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.max_ongoing_requests = max_ongoing_requests
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config
        self.placement_strategy = placement_strategy
        # Constructor overrides merged over bind() kwargs at deploy time:
        # config-file deploys tune replica knobs (e.g. the LLM engine's
        # num_slots / sync_every / use_decode_kernel) without editing the
        # application module.
        self.init_kwargs = dict(init_kwargs or {})

    def options(self, *, num_replicas: Optional[Any] = None,
                name: Optional[str] = None,
                max_ongoing_requests: Optional[int] = None,
                autoscaling_config: Optional[Dict[str, Any]] = None,
                placement_strategy: Optional[str] = None,
                ray_actor_options: Optional[Dict] = None,
                init_kwargs: Optional[Dict[str, Any]] = None,
                **_) -> "Deployment":
        return Deployment(
            self._cls_or_fn, name or self.name,
            num_replicas or self.num_replicas,
            max_ongoing_requests or self.max_ongoing_requests,
            ray_actor_options if ray_actor_options is not None
            else self.ray_actor_options,
            autoscaling_config if autoscaling_config is not None
            else self.autoscaling_config,
            placement_strategy or self.placement_strategy,
            init_kwargs if init_kwargs is not None else self.init_kwargs)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_cls=None, *, name: Optional[str] = None,
               num_replicas: Any = 1, max_ongoing_requests: int = 100,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               placement_strategy: Optional[str] = None,
               ray_actor_options: Optional[Dict] = None,
               **kwargs):
    """``@serve.deployment`` decorator (class or function).

    ``num_replicas="auto"`` or an ``autoscaling_config`` dict (min_replicas,
    max_replicas, target_ongoing_requests, upscale/downscale_delay_s)
    enables autoscaling (reference: serve autoscaling_policy.py).
    """

    def decorate(cls_or_fn):
        return Deployment(cls_or_fn, name or cls_or_fn.__name__,
                          num_replicas, max_ongoing_requests,
                          ray_actor_options=ray_actor_options,
                          autoscaling_config=autoscaling_config,
                          placement_strategy=placement_strategy)

    if _cls is not None:
        return decorate(_cls)
    return decorate


def _get_or_start_controller():
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        controller_cls = ray_tpu.remote(ServeController)
        return controller_cls.options(
            name=CONTROLLER_NAME, lifetime="detached", max_concurrency=16,
            get_if_exists=True).remote()


def _resolve_bound_args(controller, value, deployed: Dict[str, Any]):
    """Replace nested bound ``Application``s (anywhere in args, including
    inside lists/tuples/dicts) with handles to their freshly-deployed
    deployments — depth-first, so leaves deploy before their consumers
    (reference: ``build_app`` recursion, serve/_private/build_app.py:68)."""
    if isinstance(value, Application):
        return _deploy_application(controller, value, deployed)
    if isinstance(value, (list, tuple)):
        return type(value)(
            _resolve_bound_args(controller, v, deployed) for v in value)
    if isinstance(value, dict):
        return {k: _resolve_bound_args(controller, v, deployed)
                for k, v in value.items()}
    return value


def _deploy_application(controller, app: Application,
                        deployed: Dict[str, Any]) -> DeploymentHandle:
    dep = app.deployment
    if dep.name in deployed:
        # Diamond graphs: one deployment bound into several consumers
        # deploys once and shares its handle.
        return deployed[dep.name]
    import inspect

    args = tuple(_resolve_bound_args(controller, a, deployed)
                 for a in app.args)
    kwargs = {k: _resolve_bound_args(controller, v, deployed)
              for k, v in app.kwargs.items()}
    if dep.init_kwargs:
        # Config overrides win over bind(). Rebind positional bind()
        # args by name first, so overriding e.g. a positionally-bound
        # num_slots retunes it instead of crashing the replica with a
        # duplicate-argument TypeError.
        try:
            sig = inspect.signature(dep._cls_or_fn)
        except (TypeError, ValueError):   # C callables etc.
            sig = None
        var_kw = None if sig is None else next(
            (p.name for p in sig.parameters.values()
             if p.kind is inspect.Parameter.VAR_KEYWORD), None)
        if sig is not None and var_kw is None:
            unknown = set(dep.init_kwargs) - set(sig.parameters)
            if unknown:
                raise ValueError(
                    f"init_kwargs {sorted(unknown)} not accepted by "
                    f"{dep.name}'s constructor")
        try:
            bound = sig.bind_partial(*args, **kwargs)
            for key, value in dep.init_kwargs.items():
                if key in sig.parameters and key != var_kw:
                    bound.arguments[key] = value
                else:
                    # **kwargs catch-all: BoundArguments nests extras
                    # under the VAR_KEYWORD parameter; top-level keys
                    # would be silently dropped.
                    bound.arguments.setdefault(var_kw, {})[key] = value
            args, kwargs = bound.args, dict(bound.kwargs)
        except (TypeError, AttributeError):   # sig None / args mismatch
            kwargs = {**kwargs, **dep.init_kwargs}
    is_function = not inspect.isclass(dep._cls_or_fn)
    ray_tpu.get(controller.deploy.remote(
        dep.name, dep._cls_or_fn, args, kwargs, dep.num_replicas,
        is_function, dep.max_ongoing_requests, dep.autoscaling_config,
        dep.placement_strategy, dep.ray_actor_options),
        timeout=120)
    handle = DeploymentHandle(dep.name)
    deployed[dep.name] = handle
    return handle


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy an application GRAPH: nested bound deployments (an
    ``Application`` passed as an init arg) deploy recursively and the
    consumer receives a ``DeploymentHandle`` in their place — multi-stage
    pipelines (preprocess → LLM → postprocess) compose naturally
    (reference: ``serve.run`` + ``build_app``)."""
    controller = _get_or_start_controller()
    return _deploy_application(controller, app, {})


def get_deployment_handle(name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(name)


def drain(name: str, count: int = 1) -> int:
    """Gracefully drain ``count`` replicas of deployment ``name`` out of
    rotation (operator surface — a rolling replace): each drained
    replica stops admitting, leaves the routing ring, finishes its
    in-flight requests up to ``RAY_TPU_SERVE_DRAIN_S``, and is replaced
    by a fresh replica. Returns how many drains started."""
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(
        controller.drain_replicas.remote(name, count, "operator"),
        timeout=30)


def delete(name: str):
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.delete.remote(name), timeout=30)
    except ValueError:
        pass


def shutdown():
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass


# ------------------------------------------------------------- data plane
# The ingress implementations (asyncio HTTP + gRPC over a shared router)
# live in serve/proxy.py; these module-level helpers manage the default
# instances (reference: serve.start(http_options=...)).
_proxy = None
_grpc_proxy = None
_shared_router = None


def _router():
    global _shared_router
    if _shared_router is None:
        from ray_tpu.serve.proxy import _Router

        _shared_router = _Router()
    return _shared_router


def start_http(host: str = "127.0.0.1", port: int = 8000) -> int:
    """Start the asyncio HTTP ingress; returns the bound port."""
    global _proxy
    if _proxy is None:
        from ray_tpu.serve.proxy import AsyncHttpProxy

        _proxy = AsyncHttpProxy(host, port, router=_router())
    return _proxy.port


def stop_http():
    global _proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None


def start_grpc(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the gRPC ingress (ServeIngress service); returns the port."""
    global _grpc_proxy
    if _grpc_proxy is None:
        from ray_tpu.serve.proxy import GrpcProxy

        _grpc_proxy = GrpcProxy(host, port, router=_router())
    return _grpc_proxy.port


def stop_grpc():
    global _grpc_proxy
    if _grpc_proxy is not None:
        _grpc_proxy.stop()
        _grpc_proxy = None
