"""ray_tpu.serve: model serving (reference: ``python/ray/serve``).

Condensed re-design of SURVEY.md §3.5's architecture:

* ``ServeController`` (named actor, ``serve/_private/controller.py:84``):
  holds deployment specs, reconciles replica actors (create/kill/restart on
  death), serves the routing table to handles.
* ``Replica`` actors (``replica.py:879``): host the user callable with high
  max_concurrency (async-replica analog); ``@serve.batch`` methods batch
  concurrent calls.
* ``DeploymentHandle`` (``handle.py:625``): routes each call with
  power-of-two-choices on per-replica in-flight counts
  (``replica_scheduler/pow_2_scheduler.py:813``'s local approximation).
* HTTP ingress: an aiohttp proxy thread mapping ``POST /<deployment>`` to
  handle calls (``proxy.py:752``).
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "__serve_controller__"


class Replica:
    """Hosts one copy of the user callable."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, is_function: bool):
        self.is_function = is_function
        if is_function:
            self.instance = cls_or_fn
        else:
            self.instance = cls_or_fn(*init_args, **(init_kwargs or {}))

    def handle_request(self, method: str, args, kwargs):
        if self.is_function:
            return self.instance(*args, **kwargs)
        target = getattr(self.instance, method or "__call__")
        return target(*args, **kwargs)

    def health(self):
        return True


class ServeController:
    """Reconciles deployment specs → replica actors."""

    def __init__(self):
        self.deployments: Dict[str, Dict[str, Any]] = {}
        self.replicas: Dict[str, List[Any]] = {}
        self._stop = False
        threading.Thread(target=self._reconcile_loop, daemon=True).start()

    def deploy(self, name: str, cls_or_fn, init_args, init_kwargs,
               num_replicas: int, is_function: bool,
               max_concurrency: int) -> bool:
        self.deployments[name] = {
            "cls": cls_or_fn, "args": init_args, "kwargs": init_kwargs,
            "num_replicas": num_replicas, "is_function": is_function,
            "max_concurrency": max_concurrency,
        }
        self._reconcile_once(name)
        return True

    def delete(self, name: str) -> bool:
        self.deployments.pop(name, None)
        for r in self.replicas.pop(name, []):
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        return True

    def get_replicas(self, name: str):
        return list(self.replicas.get(name, []))

    def list_deployments(self):
        return {name: {"num_replicas": spec["num_replicas"]}
                for name, spec in self.deployments.items()}

    def _reconcile_once(self, name: str):
        spec = self.deployments.get(name)
        if spec is None:
            return
        replica_cls = ray_tpu.remote(Replica)
        current = self.replicas.setdefault(name, [])
        # Remove dead replicas (probe with a cheap health call).
        live = []
        for r in current:
            try:
                ray_tpu.get(r.health.remote(), timeout=5)
                live.append(r)
            except Exception:  # noqa: BLE001
                pass
        current = live
        while len(current) < spec["num_replicas"]:
            current.append(replica_cls.options(
                max_concurrency=spec["max_concurrency"]).remote(
                spec["cls"], spec["args"], spec["kwargs"],
                spec["is_function"]))
        while len(current) > spec["num_replicas"]:
            victim = current.pop()
            try:
                ray_tpu.kill(victim)
            except Exception:  # noqa: BLE001
                pass
        self.replicas[name] = current

    def _reconcile_loop(self):
        while not self._stop:
            time.sleep(1.0)
            for name in list(self.deployments):
                try:
                    self._reconcile_once(name)
                except Exception:  # noqa: BLE001
                    pass

    def shutdown(self):
        self._stop = True
        for name in list(self.deployments):
            self.delete(name)


class DeploymentResponse:
    """Future-like response (reference: ``DeploymentResponse``)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = 60.0):
        return ray_tpu.get(self._ref, timeout=timeout_s)

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: Optional[str] = None):
        self._name = deployment_name
        self._method = method_name
        self._replicas: List[Any] = []
        self._replicas_ts = 0.0
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._name, method_name)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _HandleMethod(self, name)

    def _refresh(self):
        now = time.monotonic()
        if now - self._replicas_ts > 2.0 or not self._replicas:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            self._replicas = ray_tpu.get(
                controller.get_replicas.remote(self._name), timeout=30)
            self._replicas_ts = now

    def _choose(self):
        """Power-of-two-choices over in-flight counts."""
        self._refresh()
        if not self._replicas:
            raise RuntimeError(f"deployment {self._name!r} has no replicas")
        with self._lock:
            if len(self._replicas) == 1:
                idx = 0
            else:
                a, b = random.sample(range(len(self._replicas)), 2)
                idx = a if self._inflight.get(a, 0) <= \
                    self._inflight.get(b, 0) else b
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
        return idx, self._replicas[idx]

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        idx, replica = self._choose()
        ref = replica.handle_request.remote(self._method, args, kwargs)

        def _done(_fut):
            with self._lock:
                self._inflight[idx] = max(self._inflight.get(idx, 1) - 1, 0)

        try:
            ref.future().add_done_callback(_done)
        except Exception:  # noqa: BLE001
            with self._lock:
                self._inflight[idx] = max(self._inflight.get(idx, 1) - 1, 0)
        return DeploymentResponse(ref)


class _HandleMethod:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.options(self._method).remote(*args, **kwargs)


class Application:
    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 max_ongoing_requests: int = 100,
                 ray_actor_options: Optional[Dict] = None):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.max_ongoing_requests = max_ongoing_requests
        self.ray_actor_options = ray_actor_options or {}

    def options(self, *, num_replicas: Optional[int] = None,
                name: Optional[str] = None,
                max_ongoing_requests: Optional[int] = None,
                **_) -> "Deployment":
        return Deployment(
            self._cls_or_fn, name or self.name,
            num_replicas or self.num_replicas,
            max_ongoing_requests or self.max_ongoing_requests,
            self.ray_actor_options)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_cls=None, *, name: Optional[str] = None, num_replicas: int = 1,
               max_ongoing_requests: int = 100, **kwargs):
    """``@serve.deployment`` decorator (class or function)."""

    def decorate(cls_or_fn):
        return Deployment(cls_or_fn, name or cls_or_fn.__name__,
                          num_replicas, max_ongoing_requests)

    if _cls is not None:
        return decorate(_cls)
    return decorate


def _get_or_start_controller():
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        controller_cls = ray_tpu.remote(ServeController)
        return controller_cls.options(
            name=CONTROLLER_NAME, lifetime="detached", max_concurrency=16,
            get_if_exists=True).remote()


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    controller = _get_or_start_controller()
    dep = app.deployment
    import inspect

    is_function = not inspect.isclass(dep._cls_or_fn)
    ray_tpu.get(controller.deploy.remote(
        dep.name, dep._cls_or_fn, app.args, app.kwargs, dep.num_replicas,
        is_function, dep.max_ongoing_requests), timeout=120)
    return DeploymentHandle(dep.name)


def get_deployment_handle(name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(name)


def delete(name: str):
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.delete.remote(name), timeout=30)
    except ValueError:
        pass


def shutdown():
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------- HTTP proxy
class _HttpProxy:
    def __init__(self, host: str, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        handles: Dict[str, DeploymentHandle] = {}

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                name = self.path.strip("/").split("/")[0]
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b"{}"
                try:
                    payload = json.loads(body) if body else {}
                    handle = handles.get(name)
                    if handle is None:
                        handle = DeploymentHandle(name)
                        handles[name] = handle
                    result = handle.remote(payload).result(timeout_s=60)
                    data = json.dumps(result).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    data = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # silence
                pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def stop(self):
        self.server.shutdown()


_proxy: Optional[_HttpProxy] = None


def start_http(host: str = "127.0.0.1", port: int = 8000) -> int:
    """Start the HTTP ingress; returns the bound port."""
    global _proxy
    if _proxy is None:
        _proxy = _HttpProxy(host, port)
    return _proxy.port


def stop_http():
    global _proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
