"""KV-block transfer plane for disaggregated prefill/decode serving.

Every cross-replica movement of paged-KV arena blocks goes through THIS
module — a tier-1 source lint (tests/test_metrics_lint.py) pins the
engine's ``export_kv_payload`` / ``import_kv_payload`` call sites to it,
so no bare channel write of arena bytes can creep in beside the journal.

The transfer is staged:

* **export** (prefill replica): :func:`export_kv` materializes the
  parked request's prompt blocks (K/V + int8 scale sidecars) into one
  host staging buffer with a crc32 manifest — zero-copy views of the
  staging bytes, never a pickle of the arena;
* **channel** (:func:`send_handoff` → :func:`receive_handoff`): the
  staging bytes ride a compiled-DAG shm channel
  (``experimental/channel.py``) created per handoff; the small manifest
  — everything except the staging bytes, plus the channel's reader
  attach-spec — returns through the ordinary control plane. When both
  engines live in one process, :func:`transfer_inproc` skips the
  channel entirely;
* **import** (decode replica): :func:`import_kv` crc-verifies the
  bytes, scatters them into (pre-)reserved arena blocks, inserts the
  prefix into the radix index, and enters the decode tick.

**Journal gating**: :func:`receive_handoff` refuses a manifest the
router has not stamped ``journaled`` (``RequestJournal.note_handoff``)
— an un-journaled transfer could bill a request twice after a death on
either side. Chaos sites ``kill_transfer`` / ``delay_transfer``
(matchable on ``stage=export|import``) fire inside the owning replica
process, so an injected death IS a real actor death the journal must
recover from.

Knobs: ``RAY_TPU_KV_TRANSFER_TIMEOUT_S`` (channel read wait, default
30), ``RAY_TPU_KV_TRANSFER_TTL_S`` (orphaned-channel reap, default
120).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["export_kv", "import_kv", "send_handoff", "receive_handoff",
           "transfer_inproc", "reap_channels", "transfer_timeout_s"]

#: Manifest keys that never ride the shm channel (the staging bytes go
#: alone; everything else IS the manifest).
_BODY_KEY = "staging"


def transfer_timeout_s() -> float:
    """Channel-read wait for the staging bytes (read per transfer so
    tests/operators retune live)."""
    return float(os.environ.get("RAY_TPU_KV_TRANSFER_TIMEOUT_S", "30"))


def _channel_ttl_s() -> float:
    return float(os.environ.get("RAY_TPU_KV_TRANSFER_TTL_S", "120"))


# Writer-side channels awaiting their (single) reader. The decode side
# unlinks the segment after reading (name-based destroy works from the
# reader); entries here only matter when the decode side never comes —
# a death mid-handoff, a dropped manifest — and are reaped past the TTL
# so orphaned shm segments cannot accumulate.
_PENDING: List[Tuple[Any, float]] = []
_PENDING_LOCK = threading.Lock()


def reap_channels(force: bool = False) -> int:
    """Destroy writer-side channels whose reader never came (or all of
    them with ``force=True`` — replica shutdown). Returns the count
    reaped. Destroying an already-unlinked segment is a no-op."""
    now = time.monotonic()
    reaped = 0
    with _PENDING_LOCK:
        keep = []
        for ch, deadline in _PENDING:
            if force or now >= deadline:
                try:
                    ch.destroy()
                except Exception:  # noqa: BLE001 — reader already unlinked
                    pass
                reaped += 1
            else:
                keep.append((ch, deadline))
        _PENDING[:] = keep
    return reaped


def _observe(direction: str, deployment: str, seconds: float,
             nbytes: int, blocks: int) -> None:
    from ray_tpu._private import metrics_defs as mdefs

    tags = {"deployment": deployment, "direction": direction}
    mdefs.SERVE_KV_TRANSFER_SECONDS.observe(seconds, tags=tags)
    mdefs.SERVE_KV_TRANSFER_BYTES.inc(max(int(nbytes), 0), tags=tags)
    mdefs.SERVE_KV_TRANSFER_BLOCKS.inc(max(int(blocks), 0), tags=tags)


# ---------------------------------------------------------------- export
def export_kv(engine, rid: int, *, deployment: str = "") -> Dict[str, Any]:
    """Export a parked request's KV blocks from a prefill-role engine as
    the versioned, crc32-manifested payload (staging bytes inline).
    Chaos site ``kv_transfer``/``stage=export`` fires BEFORE the gather,
    inside the prefill replica's process — an injected kill is a real
    prefill death mid-transfer. The caller holds the engine lock."""
    from ray_tpu._private import chaos

    if chaos.enabled():
        chaos.inject("kv_transfer", stage="export", deployment=deployment,
                     rid=rid)
    t0 = time.perf_counter()
    payload = engine.export_kv_payload(rid)
    dt = time.perf_counter() - t0
    payload["breakdown"] = {"export_s": dt}
    _observe("export", deployment, dt, payload["nbytes"],
             payload["num_blocks"])
    return payload


# ---------------------------------------------------------------- import
def import_kv(engine, payload: Dict[str, Any], *,
              reservation: Optional[int] = None,
              trace: Optional[Dict[str, Any]] = None,
              deployment: str = "") -> int:
    """Land an exported payload in a decode-role engine's arena (crc
    verified, radix-inserted, decode slot live). Chaos site
    ``kv_transfer``/``stage=import`` fires BEFORE the scatter, inside
    the decode replica's process. Returns the engine-local request id.
    The caller holds the engine lock."""
    from ray_tpu._private import chaos, metrics_defs as mdefs

    if chaos.enabled():
        chaos.inject("kv_transfer", stage="import", deployment=deployment,
                     rid=payload.get("rid"))
    t0 = time.perf_counter()
    try:
        rid = engine.import_kv_payload(
            payload, reservation=reservation, trace=trace,
            breakdown=payload.get("breakdown"))
    except ValueError as e:
        if "crc" in str(e):
            mdefs.SERVE_HANDOFFS.inc(tags={
                "deployment": deployment, "outcome": "crc_mismatch"})
        raise
    dt = time.perf_counter() - t0
    _observe("import", deployment, dt, payload.get("nbytes", 0),
             payload.get("num_blocks", 0))
    return rid


# --------------------------------------------------------------- channel
def send_handoff(engine, rid: int, *,
                 deployment: str = "") -> Dict[str, Any]:
    """Export + stage into a fresh shm channel. Returns the MANIFEST:
    the payload minus the staging bytes, plus the channel's reader
    attach-spec under ``"channel"``. The manifest crosses the ordinary
    control plane (it is small); the bytes wait in the channel until
    :func:`receive_handoff` collects them. The first write to a fresh
    channel never blocks, so the prefill replica is free the moment
    this returns. NOT yet importable: the router must journal the
    handoff and stamp ``manifest["journaled"]`` first."""
    from ray_tpu.experimental.channel import Channel

    reap_channels()
    payload = export_kv(engine, rid, deployment=deployment)
    staging = payload.pop(_BODY_KEY)
    t0 = time.perf_counter()
    ch = Channel(capacity=int(staging.nbytes) + (64 << 10), n_readers=1)
    ch.write(staging)
    dt = time.perf_counter() - t0
    with _PENDING_LOCK:
        _PENDING.append((ch, time.monotonic() + _channel_ttl_s()))
    payload["breakdown"]["channel_s"] = dt
    payload["channel"] = ch.reader(0)
    _observe("channel", deployment, dt, payload["nbytes"],
             payload["num_blocks"])
    return payload


def receive_handoff(engine, manifest: Dict[str, Any], *,
                    reservation: Optional[int] = None,
                    trace: Optional[Dict[str, Any]] = None,
                    deployment: str = "",
                    timeout_s: Optional[float] = None) -> int:
    """Collect a journaled handoff on the decode side: attach to the
    manifest's channel, read the staging bytes (accounted as the
    ``channel`` direction end-to-end — write + queue + read), unlink
    the segment, and import. Refuses manifests the router never
    journaled — the journal gate IS the exactly-once guarantee, so an
    un-stamped manifest is a programming error, not a retryable one."""
    if not manifest.get("journaled"):
        raise RuntimeError(
            "KV handoff manifest was not journaled: every cross-replica "
            "transfer must pass through RequestJournal.note_handoff "
            "(DisaggRecoverableStream) before import")
    ch = manifest["channel"]
    t0 = time.perf_counter()
    staging = ch.read(timeout=timeout_s if timeout_s is not None
                      else transfer_timeout_s())
    try:
        ch.destroy()          # consumed: unlink the shm segment
    except Exception:  # noqa: BLE001 — writer may have reaped first
        pass
    dt = time.perf_counter() - t0
    payload = {k: v for k, v in manifest.items()
               if k not in ("channel", "journaled")}
    payload[_BODY_KEY] = staging
    payload.setdefault("breakdown", {})
    payload["breakdown"]["channel_s"] = \
        payload["breakdown"].get("channel_s", 0.0) + dt
    _observe("channel", deployment, dt, payload.get("nbytes", 0),
             payload.get("num_blocks", 0))
    return import_kv(engine, payload, reservation=reservation,
                     trace=trace, deployment=deployment)


# ------------------------------------------------------------- fast path
def transfer_inproc(src_engine, dst_engine, rid: int, *,
                    reservation: Optional[int] = None,
                    trace: Optional[Dict[str, Any]] = None,
                    deployment: str = "", journal=None) -> int:
    """Direct in-process handoff for colocated engines: export →
    (journal) → import with no channel hop — the staging buffer passes
    by reference. When a ``journal`` is supplied the handoff is noted
    on it exactly like the cross-replica path; unit/parity tests use
    this entry so the journal ledger shape matches production."""
    payload = export_kv(src_engine, rid, deployment=deployment)
    if journal is not None:
        journal.note_handoff({
            "crc32": payload.get("crc32"),
            "nbytes": payload.get("nbytes"),
            "num_blocks": payload.get("num_blocks"),
            "attempt": journal.resumes,
        })
    return import_kv(dst_engine, payload, reservation=reservation,
                     trace=trace, deployment=deployment)
