"""Declarative Serve deploys: YAML/JSON config -> running deployments.

Reference: ``python/ray/serve/schema.py`` (ServeDeploySchema) + the
``serve build`` / ``serve deploy`` CLI — a config file names applications
by import path with per-deployment overrides, so deploys are repeatable
artifacts instead of scripts.

Config shape::

    applications:
      - name: myapp                  # optional
        import_path: my_module:app   # Application or Deployment object
        args: {}                     # bound at deploy when import is a
                                     # Deployment (ignored for Application)
        deployments:                 # optional per-deployment overrides
          - name: MyDeployment
            num_replicas: 3
            max_ongoing_requests: 8
            ray_actor_options: {num_cpus: 1}
            init_kwargs:             # constructor overrides, merged over
              num_slots: 16          # bind() kwargs (e.g. the continuous
              sync_every: 8          # -batching engine knobs)
              block_size: 64         # paged-KV plane knobs ride the same
              kv_dtype: int8         # path (paged / block_size / kv_dtype
              sampling:              # / num_blocks / sampling)
                temperature: 0.7
                top_p: 0.9
    role_groups:                     # disaggregated prefill/decode: a
      - name: llm                    # LOGICAL name mapping to deployed
        prefill: llm-prefill         # (prefill, decode) deployments —
        decode: llm-decode           # the ingress classifies + splits
"""

from __future__ import annotations

import importlib
import json
import logging
from typing import Any, Dict, List

logger = logging.getLogger(__name__)

_OVERRIDABLE = ("num_replicas", "max_ongoing_requests",
                "autoscaling_config", "placement_strategy",
                "ray_actor_options", "init_kwargs")


def _load_import_path(import_path: str):
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {import_path!r} must look like 'module:attribute'")
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _apply_overrides(deployment, overrides: Dict[str, Any]):
    """Return a COPY of the deployment with overrides applied — mutating
    the imported module-global Deployment would leak this config's values
    into every later deploy in the process."""
    kwargs: Dict[str, Any] = {}
    for key, value in overrides.items():
        if key == "name":
            continue
        if key not in _OVERRIDABLE:
            raise ValueError(f"unknown deployment override {key!r} "
                             f"(supported: {_OVERRIDABLE})")
        if key in ("num_replicas", "max_ongoing_requests"):
            kwargs[key] = int(value)
        elif key in ("autoscaling_config", "ray_actor_options",
                     "init_kwargs"):
            kwargs[key] = dict(value)
        else:
            kwargs[key] = value
    return deployment.options(**kwargs) if kwargs else deployment


def deploy_config_data(text: str) -> List[str]:
    """Deploy from a YAML/JSON document string; returns deployed names."""
    try:
        cfg = json.loads(text)
    except json.JSONDecodeError:
        import yaml

        cfg = yaml.safe_load(text)
    return deploy_config_dict(cfg or {})


def deploy_config_file(path: str) -> List[str]:
    with open(path) as f:
        return deploy_config_data(f.read())


def deploy_config_dict(cfg: Dict[str, Any]) -> List[str]:
    from ray_tpu.serve.api import (Application, Deployment,
                                   register_role_group, run)

    deployed: List[str] = []
    for app_cfg in cfg.get("applications", []):
        target = _load_import_path(app_cfg["import_path"])
        if isinstance(target, Deployment):
            args = app_cfg.get("args", {})
            target = target.bind(**args) if isinstance(args, dict) \
                else target.bind(*args)
        if not isinstance(target, Application):
            raise TypeError(
                f"{app_cfg['import_path']} resolved to {type(target)}; "
                f"expected a Deployment or a bound Application")
        dep = target.deployment
        for ov in app_cfg.get("deployments", []):
            if ov.get("name", dep.name) == dep.name:
                dep = _apply_overrides(dep, ov)
        if dep is not target.deployment:
            target = Application(dep, target.args, target.kwargs)
        run(target, name=app_cfg.get("name", dep.name))
        deployed.append(dep.name)
        logger.info("deployed %s from %s", dep.name,
                    app_cfg["import_path"])
    for group in cfg.get("role_groups", []):
        # Declared AFTER the applications deploy so the pair the group
        # names already exists when the first classified request lands.
        register_role_group(group["name"], prefill=group["prefill"],
                            decode=group["decode"])
        logger.info("registered role group %s -> prefill=%s decode=%s",
                    group["name"], group["prefill"], group["decode"])
    return deployed


def build_config(*apps) -> Dict[str, Any]:
    """Emit a deployable config dict from Application objects
    (reference: ``serve build``). import_path must be filled in by the
    caller for anything not importable by name."""
    out = {"applications": []}
    for app in apps:
        dep = app.deployment
        mod = getattr(dep._cls_or_fn, "__module__", "__main__")
        qual = getattr(dep._cls_or_fn, "__qualname__", dep.name)
        out["applications"].append({
            "name": dep.name,
            "import_path": f"{mod}:{qual}",
            "deployments": [{
                "name": dep.name,
                "num_replicas": dep.num_replicas,
                "max_ongoing_requests": dep.max_ongoing_requests,
            }],
        })
    return out


__all__ = ["deploy_config_file", "deploy_config_data",
           "deploy_config_dict", "build_config"]
