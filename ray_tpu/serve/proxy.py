"""Serve data plane: asyncio HTTP ingress + gRPC ingress over one router.

Reference: ``python/ray/serve/_private/proxy.py`` — the reference runs a
uvicorn/asyncio HTTP proxy (:752) and a gRPC proxy (:532) that share
routing state. This build keeps that shape with stdlib asyncio streams:

* keep-alive HTTP/1.1 with pipelined request loop per connection;
* chunked NDJSON streaming whose writes apply real backpressure
  (``await writer.drain()`` — a slow client throttles the generator pull
  instead of buffering unboundedly);
* a bounded executor bridging the blocking DeploymentHandle router calls,
  whose size caps in-flight requests (the asyncio analog of the
  reference's ``max_ongoing_requests`` admission);
* control endpoints: ``GET /-/healthz``, ``GET /-/routes``, and
  ``PUT /-/deploy`` (declarative config — reference ``serve deploy``).

The gRPC ingress (``GrpcProxy``) serves the same deployments through
``ServeIngress.Predict`` / ``PredictStream`` (reference grpc proxy).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_MAX_BODY = 64 << 20
_STREAM_END = object()


class _Router:
    """Shared deployment-handle cache for every ingress."""

    def __init__(self):
        self._handles: Dict[str, object] = {}
        self._lock = threading.Lock()

    def handle(self, name: str):
        from ray_tpu.serve.api import DeploymentHandle

        with self._lock:
            h = self._handles.get(name)
            if h is None:
                h = self._handles[name] = DeploymentHandle(name)
            return h

    @staticmethod
    def _check_public(method: Optional[str]) -> None:
        # Only public methods are network-routable — enforced here so
        # EVERY ingress (HTTP and gRPC) shares the guard.
        if method and method.startswith("_"):
            raise LookupError("method not found")

    def call(self, name: str, method: Optional[str], payload,
             model_id: str = "", timeout_s: float = 60.0,
             request_ctx: Optional[Dict[str, Any]] = None):
        self._check_public(method)
        h = self.handle(name).options(method,
                                      multiplexed_model_id=model_id,
                                      request_context=request_ctx)
        return h.remote(payload).result(timeout_s=timeout_s)

    def stream(self, name: str, method: Optional[str], payload,
               model_id: str = "",
               request_ctx: Optional[Dict[str, Any]] = None):
        self._check_public(method)
        h = self.handle(name).options(method, stream=True,
                                      multiplexed_model_id=model_id,
                                      request_context=request_ctx)
        gen = h.remote(payload)
        gen._timeout = 60.0  # per-item bound, like result()
        return iter(gen)


def ingress_request_context(deployment: str, tenant: str = "",
                            request_id: str = "") -> Optional[Dict[str, Any]]:
    """Mint the serve request context at an INGRESS: a fresh trace id
    plus a pre-allocated ingress span id the ingress closes when the
    response completes. Returns None when tracing is disabled (the data
    plane then pays one env check per request and nothing else). An
    ``x-request-id`` supplied by the client is honored so external
    systems can correlate."""
    if not tracing.enabled():
        return None
    return {"request_id": request_id or tracing.gen_id(),
            "trace_id": tracing.gen_id(),
            "parent_span_id": tracing.gen_id(),  # = the ingress span id
            "deployment": deployment, "tenant": tenant}


def _close_ingress_span(rctx: Optional[Dict[str, Any]], t0: float,
                        status: Any, path: str) -> None:
    """Emit the root serve.ingress span retrospectively (the span covers
    parse -> route -> full response write, so its id must exist before
    its duration does)."""
    if rctx is None:
        return
    tracing.emit_span("serve.ingress", trace_id=rctx["trace_id"],
                      span_id=rctx["parent_span_id"], ts=t0,
                      dur=time.time() - t0, kind="ingress",
                      request_id=rctx["request_id"],
                      deployment=rctx.get("deployment", ""),
                      http_path=path, status=str(status))


class AsyncHttpProxy:
    """Asyncio HTTP/1.1 ingress (keep-alive, streaming, backpressure)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 max_concurrency: int = 64, router: Optional[_Router] = None):
        self.router = router or _Router()
        # The executor bounds concurrent blocking router calls: requests
        # beyond it queue in asyncio (cheap futures), not in threads.
        self._pool = ThreadPoolExecutor(max_workers=max_concurrency,
                                        thread_name_prefix="serve-http")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._host, self._want_port = host, port
        self.port: int = 0
        self._server = None
        self._boot_error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-http-loop")
        self._thread.start()
        if not self._started.wait(10) or self.port == 0:
            if self._boot_error is not None:
                raise RuntimeError(
                    f"HTTP proxy failed to bind {host}:{port}: "
                    f"{self._boot_error}") from self._boot_error
            raise RuntimeError("HTTP proxy failed to start")

    def _run(self):
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._want_port)
            self.port = self._server.sockets[0].getsockname()[1]

        try:
            self._loop.run_until_complete(boot())
        except BaseException as e:  # noqa: BLE001 — surface bind errors
            self._boot_error = e
            self._started.set()
            self._loop.close()
            return
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    # ------------------------------------------------------------- parsing
    async def _read_request(self, reader):
        """One request, or None on clean EOF, or (status, message) for a
        protocol error the connection must answer-then-close."""
        try:
            line = await reader.readline()
        except (asyncio.LimitOverrunError, ValueError):
            return 431, "request line too long"
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, path, version = line.decode("latin1").strip().split(" ", 2)
        except ValueError:
            return 400, "malformed request line"
        headers: Dict[str, str] = {}
        while True:
            try:
                h = await reader.readline()
            except (asyncio.LimitOverrunError, ValueError):
                return 431, "header too long"
            if not h or h in (b"\r\n", b"\n"):
                break
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        if "chunked" in headers.get("transfer-encoding", "").lower():
            # Parsing chunked request bodies is unimplemented; accepting
            # the request with an empty body would desync the keep-alive
            # loop (the body bytes would parse as the next request line).
            return 501, "chunked request bodies are not supported"
        try:
            length = int(headers.get("content-length", 0) or 0)
        except ValueError:
            return 400, "malformed Content-Length"
        if length < 0:
            return 400, "malformed Content-Length"
        if length > _MAX_BODY:
            return 413, "request body too large"
        body = await reader.readexactly(length) if length else b""
        return method, path, version, headers, body

    @staticmethod
    def _response(status: int, body: bytes,
                  content_type: str = "application/json",
                  keep_alive: bool = True) -> bytes:
        import http as _http

        try:
            reason = _http.HTTPStatus(status).phrase
        except ValueError:
            reason = "Unknown"
        conn = "keep-alive" if keep_alive else "close"
        return (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: {conn}\r\n\r\n").encode() + body

    # ---------------------------------------------------------- connection
    async def _handle_conn(self, reader, writer):
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                if req is None:
                    return
                if len(req) == 2:  # protocol error: answer, then close
                    status, msg = req
                    writer.write(self._response(
                        status, json.dumps({"error": msg}).encode(),
                        keep_alive=False))
                    await writer.drain()
                    return
                method, path, version, headers, body = req
                close = (headers.get("connection", "").lower() == "close"
                         or version == "HTTP/1.0")
                try:
                    done = await self._route(method, path, headers, body,
                                             writer, keep_alive=not close)
                except (ConnectionError, asyncio.CancelledError):
                    return
                except Exception as e:  # noqa: BLE001
                    data = json.dumps({"error": str(e)}).encode()
                    writer.write(self._response(500, data,
                                                keep_alive=not close))
                    await writer.drain()
                    done = True
                if not done or close:
                    return
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _route(self, method: str, path: str, headers, body: bytes,
                     writer, keep_alive: bool) -> bool:
        """Handle one request; returns False to drop the connection."""
        loop = asyncio.get_running_loop()
        path = path.split("?", 1)[0]
        if method == "GET" and path == "/-/healthz":
            writer.write(self._response(200, b'{"status":"ok"}',
                                        keep_alive=keep_alive))
            await writer.drain()
            return True
        if method == "GET" and path == "/-/routes":
            routes = await loop.run_in_executor(self._pool, _list_routes)
            writer.write(self._response(
                200, json.dumps(routes).encode(), keep_alive=keep_alive))
            await writer.drain()
            return True
        if method in ("PUT", "POST") and path == "/-/deploy":
            from ray_tpu.serve.config import deploy_config_data

            cfg = await loop.run_in_executor(
                self._pool, deploy_config_data, body.decode())
            writer.write(self._response(
                200, json.dumps({"deployed": cfg}).encode(),
                keep_alive=keep_alive))
            await writer.drain()
            return True
        if method != "POST":
            writer.write(self._response(404, b'{"error":"not found"}',
                                        keep_alive=keep_alive))
            await writer.drain()
            return True

        parts = path.strip("/").split("/")
        name = parts[0]
        stream = len(parts) >= 2 and parts[1] == "stream"
        call_method = (parts[2] if stream and len(parts) > 2 else
                       parts[1] if len(parts) > 1 else None)
        if not name or (call_method and call_method.startswith("_")):
            writer.write(self._response(
                404, json.dumps({"error": "method not found"}).encode(),
                keep_alive=keep_alive))
            await writer.drain()
            return True
        model_id = headers.get("serve_multiplexed_model_id", "")
        payload = json.loads(body) if body else {}
        # Request-path tracing starts HERE: the ingress mints the trace
        # context (one trace per request) and every downstream hop —
        # route decision, replica dispatch, engine admission, prefill,
        # decode windows — parents into it.
        rctx = ingress_request_context(
            name, tenant=model_id,
            request_id=headers.get("x-request-id", ""))
        ing_t0 = time.time()

        if not stream:
            try:
                result = await loop.run_in_executor(
                    self._pool, self.router.call, name, call_method,
                    payload, model_id, 60.0, rctx)
            except Exception:
                _close_ingress_span(rctx, ing_t0, "error", path)
                raise
            writer.write(self._response(
                200, json.dumps(result).encode(), keep_alive=keep_alive))
            await writer.drain()
            _close_ingress_span(rctx, ing_t0, 200, path)
            return True

        # Streaming: pull the first item BEFORE committing to 200 so
        # pre-stream failures surface as errors, not empty streams.
        try:
            items = await loop.run_in_executor(
                self._pool, self.router.stream, name, call_method,
                payload, model_id, rctx)
        except Exception:
            _close_ingress_span(rctx, ing_t0, "error", path)
            raise

        def pull():
            try:
                return next(items)
            except StopIteration:
                return _STREAM_END

        try:
            first = await loop.run_in_executor(self._pool, pull)
        except Exception:
            _close_ingress_span(rctx, ing_t0, "error", path)
            raise
        conn = "keep-alive" if keep_alive else "close"
        writer.write((f"HTTP/1.1 200 OK\r\n"
                      f"Content-Type: application/x-ndjson\r\n"
                      f"Transfer-Encoding: chunked\r\n"
                      f"Connection: {conn}\r\n\r\n").encode())
        item = first
        try:
            while item is not _STREAM_END:
                chunk = json.dumps(item).encode() + b"\n"
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                             + b"\r\n")
                await writer.drain()  # backpressure: slow client, slow pull
                item = await loop.run_in_executor(self._pool, pull)
            writer.write(b"0\r\n\r\n")
            await writer.drain()
            _close_ingress_span(rctx, ing_t0, 200, path)
            return True
        except Exception:  # noqa: BLE001 — mid-stream failure: abort the
            # connection so the client sees truncation, not completion.
            logger.exception("streaming response for %s failed mid-stream",
                             name)
            _close_ingress_span(rctx, ing_t0, "aborted", path)
            return False

    def stop(self):
        def _shutdown():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)


def _list_routes() -> Dict[str, str]:
    import ray_tpu
    from ray_tpu.serve.api import CONTROLLER_NAME

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        deployments = ray_tpu.get(controller.list_deployments.remote(),
                                  timeout=10)
        return {f"/{d}": d for d in deployments}
    except Exception:  # noqa: BLE001
        return {}


class GrpcProxy:
    """gRPC ingress sharing the HTTP router (reference: grpc proxy,
    ``serve/_private/proxy.py:532``). Payloads are JSON bytes; streaming
    deployments map to a server-streaming RPC."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 router: Optional[_Router] = None):
        from ray_tpu._private import rpc

        self.router = router or _Router()
        self._server, self.port = rpc.serve("ServeIngress", self, port=port,
                                            host=host)

    # ------------------------------------------------------------ handlers
    def Predict(self, request, context):
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        rctx = ingress_request_context(
            request.deployment, tenant=request.multiplexed_model_id)
        t0 = time.time()
        try:
            payload = json.loads(request.payload) if request.payload else {}
            result = self.router.call(
                request.deployment, request.method or None, payload,
                request.multiplexed_model_id, request_ctx=rctx)
            _close_ingress_span(rctx, t0, "ok", "grpc:Predict")
            return pb.ServeReply(ok=True,
                                 payload=json.dumps(result).encode())
        except Exception as e:  # noqa: BLE001
            _close_ingress_span(rctx, t0, "error", "grpc:Predict")
            return pb.ServeReply(ok=False, error=str(e))

    def PredictStream(self, request, context):
        import grpc as _grpc

        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        rctx = ingress_request_context(
            request.deployment, tenant=request.multiplexed_model_id)
        t0 = time.time()
        status = "aborted"  # client cancellation raises GeneratorExit,
        try:                # which except Exception would never see
            payload = json.loads(request.payload) if request.payload else {}
            items = self.router.stream(
                request.deployment, request.method or None, payload,
                request.multiplexed_model_id, request_ctx=rctx)
            for item in items:
                yield pb.ServeReply(ok=True,
                                    payload=json.dumps(item).encode())
            status = "ok"
        except Exception as e:  # noqa: BLE001
            # Terminate with an RPC error, NOT a trailing ok=False item:
            # consumers filtering on ok would read a truncated stream as a
            # successful short one (the HTTP plane aborts the connection
            # for the same reason).
            status = "error"
            context.abort(_grpc.StatusCode.INTERNAL, str(e))
        finally:
            _close_ingress_span(rctx, t0, status, "grpc:PredictStream")

    def stop(self):
        self._server.stop(grace=0.5)


__all__ = ["AsyncHttpProxy", "GrpcProxy", "ingress_request_context"]
