"""Serve data plane: asyncio HTTP ingress + gRPC ingress over one router.

Reference: ``python/ray/serve/_private/proxy.py`` — the reference runs a
uvicorn/asyncio HTTP proxy (:752) and a gRPC proxy (:532) that share
routing state. This build keeps that shape with stdlib asyncio streams:

* keep-alive HTTP/1.1 with pipelined request loop per connection;
* chunked NDJSON streaming whose writes apply real backpressure
  (``await writer.drain()`` — a slow client throttles the generator pull
  instead of buffering unboundedly);
* a bounded executor bridging the blocking DeploymentHandle router calls,
  whose size caps in-flight requests (the asyncio analog of the
  reference's ``max_ongoing_requests`` admission);
* control endpoints: ``GET /-/healthz``, ``GET /-/routes``, and
  ``PUT /-/deploy`` (declarative config — reference ``serve deploy``).

The gRPC ingress (``GrpcProxy``) serves the same deployments through
``ServeIngress.Predict`` / ``PredictStream`` (reference grpc proxy).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_MAX_BODY = 64 << 20
_STREAM_END = object()


def prefix_fingerprint(payload: Any) -> str:
    """Prefix fingerprint of an LLM request: a hash of the first k
    block-aligned chunks of ``prompt_token_ids`` (chunk size
    ``RAY_TPU_PREFIX_FP_CHUNK``, default 64 — the engine's default KV
    block size — over at most ``RAY_TPU_PREFIX_FP_CHUNKS`` chunks).
    Requests sharing a system prompt hash identically, so the router
    can keep them on the replica whose radix cache already holds the
    prefix. Returns "" for non-LLM payloads and prompts shorter than
    one chunk (nothing block-aligned to share). Collisions only cost
    routing locality — the engine's radix index matches exact token
    tuples, never hashes."""
    if not isinstance(payload, dict):
        return ""
    ids = payload.get("prompt_token_ids")
    if not isinstance(ids, (list, tuple)):
        return ""
    chunk = int(os.environ.get("RAY_TPU_PREFIX_FP_CHUNK", "64"))
    max_chunks = int(os.environ.get("RAY_TPU_PREFIX_FP_CHUNKS", "4"))
    k = min(max_chunks, len(ids) // max(chunk, 1))
    if k <= 0:
        return ""
    try:
        head = ",".join(str(int(t)) for t in ids[:k * chunk])
    except (TypeError, ValueError):
        return ""
    return f"{zlib.crc32(head.encode()):08x}"


class AdmissionGate:
    """Ingress admission control: per-tenant token buckets + pressure-
    thresholded load shedding. At saturation the fabric answers 429 +
    Retry-After (gRPC: RESOURCE_EXHAUSTED) instead of queueing
    unboundedly — clients get an honest back-off signal while admitted
    traffic keeps its latency. Pressure comes from the router handle's
    TTL-cached controller snapshots, so the per-request cost is a clock
    read and a few dict lookups.

    Thresholds (env, read per decision so tests and operators can
    retune live):

    * ``RAY_TPU_SHED_QUEUE_DEPTH`` — shed when EVERY reachable replica's
      congestion (engine queue depth + router ongoing, plus an
      arena-exhausted penalty) is at/above this. 0 disables pressure
      shedding (default 32).
    * ``RAY_TPU_SHED_RETRY_AFTER_S`` — advertised back-off (default 1).
    """

    def __init__(self, router: "_Router"):
        self._router = router

    @staticmethod
    def _congestion(snap: Dict[str, Any]) -> float:
        cost = float(snap.get("queue_depth") or 0)
        cost += float(snap.get("ongoing") or 0)
        total = snap.get("kv_blocks_total") or 0
        if total:
            avail = ((snap.get("kv_blocks_free") or 0)
                     + (snap.get("kv_blocks_cached") or 0))
            if avail <= 0:
                # Nothing to admit with even after LRU reclaim: the
                # next request can only queue.
                cost = max(cost, 1e9)
        return cost

    def check(self, deployment: str,
              tenant: str = "") -> Optional[Tuple[float, str]]:
        """None = admit; else ``(retry_after_s, reason)`` with reason in
        {"tenant_rate_limit", "pressure"} — the caller turns it into
        429 + Retry-After / RESOURCE_EXHAUSTED and the rejection is
        tagged into ``ray_tpu_serve_request_outcomes_total``."""
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.serve import multiplex

        # Pressure first: a pressure shed is the FABRIC's fault, so it
        # must not consume the tenant's bucket — otherwise a saturated
        # window drains every tenant's quota and their honest retries
        # bounce on tenant_rate_limit after pressure clears.
        shed = self._pressure_shed(deployment)
        if shed is not None:
            mdefs.SERVE_REQ_OUTCOMES.inc(tags={
                "deployment": deployment, "tenant": tenant,
                "engine": "ingress", "outcome": "shed_pressure"})
            return shed, "pressure"
        wait = multiplex.tenant_rate_limiter().try_acquire(tenant)
        if wait is not None:
            mdefs.SERVE_REQ_OUTCOMES.inc(tags={
                "deployment": deployment, "tenant": tenant,
                "engine": "ingress", "outcome": "shed_tenant"})
            return max(wait, 0.05), "tenant_rate_limit"
        return None

    def _pressure_shed(self, deployment: str) -> Optional[float]:
        """Retry-after seconds when EVERY reachable replica is at/above
        the shed threshold; None (admit) otherwise — failing open
        whenever pressure data is off, missing, or unreachable."""
        threshold = float(os.environ.get("RAY_TPU_SHED_QUEUE_DEPTH",
                                         "32") or 0)
        if threshold <= 0:
            return None
        try:
            from ray_tpu.serve import api as serve_api

            # A role-group (disaggregated) name has no replicas of its
            # own: the decode group's pressure is the admission signal
            # (its arena is where every request ultimately lives).
            group = serve_api.get_role_group(deployment)
            target = group["decode"] if group else deployment
            snaps = self._router.handle(target)._fetch_shared_pressure()
        except Exception:  # noqa: BLE001 — no controller: fail open
            return None
        reachable = [s for s in snaps
                     if s and not s.get("unreachable")]
        if not reachable:
            return None          # no pressure data: fail open
        if all(self._congestion(s) >= threshold for s in reachable):
            return float(os.environ.get("RAY_TPU_SHED_RETRY_AFTER_S",
                                        "1.0"))
        return None


class _Router:
    """Shared deployment-handle cache for every ingress."""

    #: Recently-dispatched prefix fingerprints the classifier treats as
    #: probably-cached on the decode side (bounded LRU).
    FP_SEEN_CAP = 512

    def __init__(self):
        self._handles: Dict[str, object] = {}
        self._lock = threading.Lock()
        # One admission gate per router: HTTP and gRPC ingresses share
        # its (handle-cached) pressure view and tenant buckets.
        self.gate = AdmissionGate(self)
        # Fingerprint → last-seen order, for the disagg classifier's
        # net-prefill estimate (OrderedDict as LRU).
        from collections import OrderedDict

        self._fp_seen: "OrderedDict[str, None]" = OrderedDict()

    def handle(self, name: str):
        from ray_tpu.serve.api import DeploymentHandle

        with self._lock:
            h = self._handles.get(name)
            if h is None:
                h = self._handles[name] = DeploymentHandle(name)
            return h

    @staticmethod
    def _check_public(method: Optional[str]) -> None:
        # Only public methods are network-routable — enforced here so
        # EVERY ingress (HTTP and gRPC) shares the guard.
        if method and method.startswith("_"):
            raise LookupError("method not found")

    # ------------------------------------------- disaggregated classify
    def _note_fp(self, fp: str) -> None:
        with self._lock:
            self._fp_seen.pop(fp, None)
            self._fp_seen[fp] = None
            while len(self._fp_seen) > self.FP_SEEN_CAP:
                self._fp_seen.popitem(last=False)

    def _classify_disagg(self, group: Dict[str, str], payload) -> bool:
        """True → split dispatch (prefill replica → KV handoff → decode
        replica); False → the decode group runs the request colocated.
        The estimate: NET prefill cost = prompt tokens minus the
        fingerprint-matched prefix a decode replica likely already
        holds (a seen fingerprint means its block-aligned head is hot
        in some radix cache — re-prefilling it locally is cheap, so it
        doesn't justify a transfer). Split when the net cost clears
        ``RAY_TPU_DISAGG_PREFILL_THRESHOLD`` tokens (default 128; <=0
        splits every LLM request — the parity/chaos tests' mode), or
        when the LIVE pressure feed shows every decode replica already
        queueing ``RAY_TPU_DISAGG_QUEUE_TOKENS`` prefill tokens (>0
        enables) — colocated admission would stall their decode ticks
        regardless of this prompt's size."""
        prompt = payload.get("prompt_token_ids") or ()
        plen = len(prompt)
        fp = prefix_fingerprint(payload)
        covered = 0
        if fp:
            chunk = int(os.environ.get("RAY_TPU_PREFIX_FP_CHUNK", "64"))
            max_chunks = int(os.environ.get("RAY_TPU_PREFIX_FP_CHUNKS",
                                            "4"))
            with self._lock:
                seen = fp in self._fp_seen
            if seen:
                covered = min(plen,
                              min(max_chunks, plen // max(chunk, 1))
                              * chunk)
            self._note_fp(fp)
        net_prefill = plen - covered
        threshold = float(os.environ.get(
            "RAY_TPU_DISAGG_PREFILL_THRESHOLD", "128"))
        if net_prefill >= threshold:
            return True
        floor = float(os.environ.get("RAY_TPU_DISAGG_QUEUE_TOKENS",
                                     "0") or 0)
        if floor > 0:
            try:
                snaps = self.handle(
                    group["decode"])._fetch_shared_pressure()
            except Exception:  # noqa: BLE001 — no feed: size-only rule
                snaps = []
            live = [s for s in snaps if s and not s.get("unreachable")]
            if live and all(
                    float(s.get("prefill_queue_tokens") or 0) >= floor
                    for s in live):
                return True
        return False

    def call(self, name: str, method: Optional[str], payload,
             model_id: str = "", timeout_s: float = 60.0,
             request_ctx: Optional[Dict[str, Any]] = None):
        from ray_tpu.serve import api as serve_api

        self._check_public(method)
        group = serve_api.get_role_group(name)
        if group is not None:
            # Unary completions run colocated on the decode group (its
            # engines accept plain submits); only streams split.
            name = group["decode"]
        h = self.handle(name).options(
            method, multiplexed_model_id=model_id,
            request_context=request_ctx,
            prefix_key=prefix_fingerprint(payload))
        return h.remote(payload).result(timeout_s=timeout_s)

    def stream(self, name: str, method: Optional[str], payload,
               model_id: str = "",
               request_ctx: Optional[Dict[str, Any]] = None):
        """Streaming dispatch through the RECOVERY JOURNAL: the returned
        iterator survives replica death (queued/prefilling requests
        resubmit; mid-decode LLM requests resume as prompt + emitted
        tokens, exactly-once under greedy decoding) and drain rejects
        re-route for free. The iterator's ``.journal`` tells the ingress
        whether to surface the ``x-ray-tpu-resumed`` marker.

        A name registered as a ROLE GROUP classifies first: requests
        whose estimated net prefill cost justifies the transfer split
        across the (prefill, decode) pair with a journaled KV handoff
        (:class:`~ray_tpu.serve.recovery.DisaggRecoverableStream`);
        the rest run colocated on the decode group."""
        from ray_tpu.serve import api as serve_api
        from ray_tpu.serve.recovery import (DisaggRecoverableStream,
                                            RecoverableStream,
                                            RequestJournal,
                                            is_llm_payload)

        self._check_public(method)
        group = serve_api.get_role_group(name)
        if group is not None:
            if is_llm_payload(payload) and \
                    self._classify_disagg(group, payload):
                journal = RequestJournal(name, method, payload,
                                         model_id=model_id,
                                         request_ctx=request_ctx)
                return DisaggRecoverableStream(
                    self.handle(group["prefill"]),
                    self.handle(group["decode"]),
                    journal, per_item_timeout_s=60.0)
            name = group["decode"]
        journal = RequestJournal(name, method, payload,
                                 model_id=model_id,
                                 request_ctx=request_ctx)
        return RecoverableStream(self.handle(name), journal,
                                 per_item_timeout_s=60.0)


def ingress_request_context(deployment: str, tenant: str = "",
                            request_id: str = "") -> Optional[Dict[str, Any]]:
    """Mint the serve request context at an INGRESS: a fresh trace id
    plus a pre-allocated ingress span id the ingress closes when the
    response completes. Returns None when tracing is disabled (the data
    plane then pays one env check per request and nothing else). An
    ``x-request-id`` supplied by the client is honored so external
    systems can correlate."""
    if not tracing.enabled():
        return None
    return {"request_id": request_id or tracing.gen_id(),
            "trace_id": tracing.gen_id(),
            "parent_span_id": tracing.gen_id(),  # = the ingress span id
            "deployment": deployment, "tenant": tenant}


def _close_ingress_span(rctx: Optional[Dict[str, Any]], t0: float,
                        status: Any, path: str) -> None:
    """Emit the root serve.ingress span retrospectively (the span covers
    parse -> route -> full response write, so its id must exist before
    its duration does)."""
    if rctx is None:
        return
    tracing.emit_span("serve.ingress", trace_id=rctx["trace_id"],
                      span_id=rctx["parent_span_id"], ts=t0,
                      dur=time.time() - t0, kind="ingress",
                      request_id=rctx["request_id"],
                      deployment=rctx.get("deployment", ""),
                      http_path=path, status=str(status))


class AsyncHttpProxy:
    """Asyncio HTTP/1.1 ingress (keep-alive, streaming, backpressure)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 max_concurrency: int = 64, router: Optional[_Router] = None):
        self.router = router or _Router()
        # The executor bounds concurrent blocking router calls: requests
        # beyond it queue in asyncio (cheap futures), not in threads.
        self._pool = ThreadPoolExecutor(max_workers=max_concurrency,
                                        thread_name_prefix="serve-http")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._host, self._want_port = host, port
        self.port: int = 0
        self._server = None
        self._boot_error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-http-loop")
        self._thread.start()
        if not self._started.wait(10) or self.port == 0:
            if self._boot_error is not None:
                raise RuntimeError(
                    f"HTTP proxy failed to bind {host}:{port}: "
                    f"{self._boot_error}") from self._boot_error
            raise RuntimeError("HTTP proxy failed to start")

    def _run(self):
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._want_port)
            self.port = self._server.sockets[0].getsockname()[1]

        try:
            self._loop.run_until_complete(boot())
        except BaseException as e:  # noqa: BLE001 — surface bind errors
            self._boot_error = e
            self._started.set()
            self._loop.close()
            return
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    # ------------------------------------------------------------- parsing
    async def _read_request(self, reader):
        """One request, or None on clean EOF, or (status, message) for a
        protocol error the connection must answer-then-close."""
        try:
            line = await reader.readline()
        except (asyncio.LimitOverrunError, ValueError):
            return 431, "request line too long"
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, path, version = line.decode("latin1").strip().split(" ", 2)
        except ValueError:
            return 400, "malformed request line"
        headers: Dict[str, str] = {}
        while True:
            try:
                h = await reader.readline()
            except (asyncio.LimitOverrunError, ValueError):
                return 431, "header too long"
            if not h or h in (b"\r\n", b"\n"):
                break
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        if "chunked" in headers.get("transfer-encoding", "").lower():
            # Parsing chunked request bodies is unimplemented; accepting
            # the request with an empty body would desync the keep-alive
            # loop (the body bytes would parse as the next request line).
            return 501, "chunked request bodies are not supported"
        try:
            length = int(headers.get("content-length", 0) or 0)
        except ValueError:
            return 400, "malformed Content-Length"
        if length < 0:
            return 400, "malformed Content-Length"
        if length > _MAX_BODY:
            return 413, "request body too large"
        body = await reader.readexactly(length) if length else b""
        return method, path, version, headers, body

    @staticmethod
    def _response(status: int, body: bytes,
                  content_type: str = "application/json",
                  keep_alive: bool = True,
                  extra_headers: Optional[Dict[str, str]] = None) -> bytes:
        import http as _http

        try:
            reason = _http.HTTPStatus(status).phrase
        except ValueError:
            reason = "Unknown"
        conn = "keep-alive" if keep_alive else "close"
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        return (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"{extra}"
                f"Connection: {conn}\r\n\r\n").encode() + body

    # ---------------------------------------------------------- connection
    async def _handle_conn(self, reader, writer):
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                if req is None:
                    return
                if len(req) == 2:  # protocol error: answer, then close
                    status, msg = req
                    writer.write(self._response(
                        status, json.dumps({"error": msg}).encode(),
                        keep_alive=False))
                    await writer.drain()
                    return
                method, path, version, headers, body = req
                close = (headers.get("connection", "").lower() == "close"
                         or version == "HTTP/1.0")
                try:
                    done = await self._route(method, path, headers, body,
                                             writer, keep_alive=not close)
                except (ConnectionError, asyncio.CancelledError):
                    return
                except Exception as e:  # noqa: BLE001
                    data = json.dumps({"error": str(e)}).encode()
                    writer.write(self._response(500, data,
                                                keep_alive=not close))
                    await writer.drain()
                    done = True
                if not done or close:
                    return
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _route(self, method: str, path: str, headers, body: bytes,
                     writer, keep_alive: bool) -> bool:
        """Handle one request; returns False to drop the connection."""
        loop = asyncio.get_running_loop()
        path = path.split("?", 1)[0]
        if method == "GET" and path == "/-/healthz":
            writer.write(self._response(200, b'{"status":"ok"}',
                                        keep_alive=keep_alive))
            await writer.drain()
            return True
        if method == "GET" and path == "/-/routes":
            routes = await loop.run_in_executor(self._pool, _list_routes)
            writer.write(self._response(
                200, json.dumps(routes).encode(), keep_alive=keep_alive))
            await writer.drain()
            return True
        if method in ("PUT", "POST") and path == "/-/deploy":
            from ray_tpu.serve.config import deploy_config_data

            cfg = await loop.run_in_executor(
                self._pool, deploy_config_data, body.decode())
            writer.write(self._response(
                200, json.dumps({"deployed": cfg}).encode(),
                keep_alive=keep_alive))
            await writer.drain()
            return True
        if method != "POST":
            writer.write(self._response(404, b'{"error":"not found"}',
                                        keep_alive=keep_alive))
            await writer.drain()
            return True

        parts = path.strip("/").split("/")
        name = parts[0]
        stream = len(parts) >= 2 and parts[1] == "stream"
        call_method = (parts[2] if stream and len(parts) > 2 else
                       parts[1] if len(parts) > 1 else None)
        if not name or (call_method and call_method.startswith("_")):
            writer.write(self._response(
                404, json.dumps({"error": "method not found"}).encode(),
                keep_alive=keep_alive))
            await writer.drain()
            return True
        model_id = headers.get("serve_multiplexed_model_id", "")
        payload = json.loads(body) if body else {}
        # ADMISSION GATE before any dispatch work: per-tenant token
        # buckets + pressure-thresholded load shedding. A saturated
        # fabric answers 429 + Retry-After so clients back off honestly
        # instead of piling into an unbounded queue.
        shed = await loop.run_in_executor(
            self._pool, self.router.gate.check, name, model_id)
        if shed is not None:
            retry_after, reason = shed
            writer.write(self._response(
                429,
                json.dumps({"error": f"overloaded: {reason}",
                            "retry_after_s": retry_after}).encode(),
                keep_alive=keep_alive,
                extra_headers={"Retry-After":
                               f"{max(retry_after, 0.05):.3f}"}))
            await writer.drain()
            return True
        # Request-path tracing starts HERE: the ingress mints the trace
        # context (one trace per request) and every downstream hop —
        # route decision, replica dispatch, engine admission, prefill,
        # decode windows — parents into it.
        rctx = ingress_request_context(
            name, tenant=model_id,
            request_id=headers.get("x-request-id", ""))
        ing_t0 = time.time()

        if not stream:
            try:
                result = await loop.run_in_executor(
                    self._pool, self.router.call, name, call_method,
                    payload, model_id, 60.0, rctx)
            except Exception:
                _close_ingress_span(rctx, ing_t0, "error", path)
                raise
            writer.write(self._response(
                200, json.dumps(result).encode(), keep_alive=keep_alive))
            await writer.drain()
            _close_ingress_span(rctx, ing_t0, 200, path)
            return True

        # Streaming: pull the first item BEFORE committing to 200 so
        # pre-stream failures surface as errors, not empty streams.
        try:
            items = await loop.run_in_executor(
                self._pool, self.router.stream, name, call_method,
                payload, model_id, rctx)
        except Exception:
            _close_ingress_span(rctx, ing_t0, "error", path)
            raise

        def pull():
            try:
                return next(items)
            except StopIteration:
                return _STREAM_END

        try:
            first = await loop.run_in_executor(self._pool, pull)
        except Exception:
            _close_ingress_span(rctx, ing_t0, "error", path)
            raise
        journal = getattr(items, "journal", None)
        conn = "keep-alive" if keep_alive else "close"
        marker_sent = False
        extra = ""
        if journal is not None and journal.needs_marker:
            # A SAMPLED request was already resumed during the first
            # pull: the continuation is a re-seeded draw, and the
            # header says so before any token reaches the client.
            from ray_tpu.serve.recovery import RESUMED_MARKER

            extra = f"{RESUMED_MARKER}: {journal.resumes}\r\n"
            marker_sent = True
        writer.write((f"HTTP/1.1 200 OK\r\n"
                      f"Content-Type: application/x-ndjson\r\n"
                      f"Transfer-Encoding: chunked\r\n"
                      f"{extra}"
                      f"Connection: {conn}\r\n\r\n").encode())
        item = first
        try:
            while item is not _STREAM_END:
                chunk = json.dumps(item).encode() + b"\n"
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                             + b"\r\n")
                await writer.drain()  # backpressure: slow client, slow pull
                item = await loop.run_in_executor(self._pool, pull)
            if journal is not None and journal.needs_marker \
                    and not marker_sent:
                # The sampled resume happened MID-stream (headers long
                # gone): a trailing NDJSON control object carries the
                # marker instead.
                from ray_tpu.serve.recovery import RESUMED_MARKER

                chunk = json.dumps(
                    {RESUMED_MARKER: journal.resumes}).encode() + b"\n"
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                             + b"\r\n")
            writer.write(b"0\r\n\r\n")
            await writer.drain()
            _close_ingress_span(rctx, ing_t0, 200, path)
            return True
        except Exception:  # noqa: BLE001 — mid-stream failure: abort the
            # connection so the client sees truncation, not completion.
            logger.exception("streaming response for %s failed mid-stream",
                             name)
            _close_ingress_span(rctx, ing_t0, "aborted", path)
            return False

    def stop(self):
        def _shutdown():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)


def _list_routes() -> Dict[str, str]:
    import ray_tpu
    from ray_tpu.serve.api import CONTROLLER_NAME

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        deployments = ray_tpu.get(controller.list_deployments.remote(),
                                  timeout=10)
        return {f"/{d}": d for d in deployments}
    except Exception:  # noqa: BLE001
        return {}


class GrpcProxy:
    """gRPC ingress sharing the HTTP router (reference: grpc proxy,
    ``serve/_private/proxy.py:532``). Payloads are JSON bytes; streaming
    deployments map to a server-streaming RPC."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 router: Optional[_Router] = None):
        from ray_tpu._private import rpc

        self.router = router or _Router()
        self._server, self.port = rpc.serve("ServeIngress", self, port=port,
                                            host=host)

    # ------------------------------------------------------------ handlers
    @staticmethod
    def _shed(context, shed) -> None:
        """Reject with RESOURCE_EXHAUSTED + the advertised back-off (the
        gRPC analog of 429 + Retry-After)."""
        import grpc as _grpc

        retry_after, reason = shed
        context.abort(_grpc.StatusCode.RESOURCE_EXHAUSTED,
                      f"overloaded: {reason}; retry after "
                      f"{retry_after:.3f}s")

    def Predict(self, request, context):
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        shed = self.router.gate.check(request.deployment,
                                      request.multiplexed_model_id)
        if shed is not None:
            self._shed(context, shed)
        rctx = ingress_request_context(
            request.deployment, tenant=request.multiplexed_model_id)
        t0 = time.time()
        try:
            payload = json.loads(request.payload) if request.payload else {}
            result = self.router.call(
                request.deployment, request.method or None, payload,
                request.multiplexed_model_id, request_ctx=rctx)
            _close_ingress_span(rctx, t0, "ok", "grpc:Predict")
            return pb.ServeReply(ok=True,
                                 payload=json.dumps(result).encode())
        except Exception as e:  # noqa: BLE001
            _close_ingress_span(rctx, t0, "error", "grpc:Predict")
            return pb.ServeReply(ok=False, error=str(e))

    def PredictStream(self, request, context):
        import grpc as _grpc

        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        shed = self.router.gate.check(request.deployment,
                                      request.multiplexed_model_id)
        if shed is not None:
            self._shed(context, shed)
        rctx = ingress_request_context(
            request.deployment, tenant=request.multiplexed_model_id)
        t0 = time.time()
        status = "aborted"  # client cancellation raises GeneratorExit,
        try:                # which except Exception would never see
            payload = json.loads(request.payload) if request.payload else {}
            items = self.router.stream(
                request.deployment, request.method or None, payload,
                request.multiplexed_model_id, request_ctx=rctx)
            for item in items:
                yield pb.ServeReply(ok=True,
                                    payload=json.dumps(item).encode())
            journal = getattr(items, "journal", None)
            if journal is not None and journal.needs_marker:
                # Sampled request resumed mid-decode: a trailing control
                # reply surfaces the re-seed (the gRPC analog of the
                # x-ray-tpu-resumed header/NDJSON marker).
                from ray_tpu.serve.recovery import RESUMED_MARKER

                yield pb.ServeReply(ok=True, payload=json.dumps(
                    {RESUMED_MARKER: journal.resumes}).encode())
            status = "ok"
        except Exception as e:  # noqa: BLE001
            # Terminate with an RPC error, NOT a trailing ok=False item:
            # consumers filtering on ok would read a truncated stream as a
            # successful short one (the HTTP plane aborts the connection
            # for the same reason).
            status = "error"
            context.abort(_grpc.StatusCode.INTERNAL, str(e))
        finally:
            _close_ingress_span(rctx, t0, status, "grpc:PredictStream")

    def stop(self):
        self._server.stop(grace=0.5)


__all__ = ["AdmissionGate", "AsyncHttpProxy", "GrpcProxy",
           "ingress_request_context", "prefix_fingerprint"]
