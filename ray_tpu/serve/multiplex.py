"""Model multiplexing: many models served by few replicas, LRU-loaded.

Reference: ``python/ray/serve/api.py`` ``@serve.multiplexed`` +
``serve/_private/multiplex.py`` ``ModelMultiplexWrapper`` — a replica
holds up to ``max_num_models_per_replica`` models in an LRU cache; the
router keeps requests for one model id on the same replica so its cache
hits. TPU-native simplification: affinity comes from consistent hashing
of the model id over the replica set (the reference pushes loaded-model
reports through the controller; hashing gives the same steady-state
locality without the feedback loop).
"""

from __future__ import annotations

import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Callable, Optional

_model_id_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Model id of the in-flight request (reference:
    ``serve.get_multiplexed_model_id``); "" outside a multiplexed call."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id)


def _reset_model_id(token) -> None:
    _model_id_ctx.reset(token)


def get_request_tenant() -> str:
    """The in-flight request's TENANT for telemetry attribution: the
    multiplexed model id ('' for single-tenant deployments). The
    ``ray_tpu_serve_request_*`` histograms carry this as their
    ``tenant`` tag so one noisy tenant's TTFT/TPOT is separable from
    the deployment aggregate. Delegates to
    :func:`get_multiplexed_model_id` — one source of truth if a
    default-tenant rule ever lands."""
    return get_multiplexed_model_id()


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate a model-loader method ``(self, model_id) -> model``: calls
    hit a per-replica LRU so at most ``max_num_models_per_replica`` models
    stay resident; older ones are evicted on overflow."""

    def decorate(loader: Callable):
        cache_attr = f"__serve_mux_cache_{loader.__name__}"

        def _cache(self) -> OrderedDict:
            cache = getattr(self, cache_attr, None)
            if cache is None:
                cache = OrderedDict()
                setattr(self, cache_attr, cache)
            return cache

        if inspect.iscoroutinefunction(loader):
            @functools.wraps(loader)
            async def wrapper(self, model_id: str):
                cache = _cache(self)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = await loader(self, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                return model
        else:
            @functools.wraps(loader)
            def wrapper(self, model_id: str):
                cache = _cache(self)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = loader(self, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                return model

        wrapper.__serve_multiplexed__ = True
        return wrapper

    if func is not None:
        return decorate(func)
    return decorate
