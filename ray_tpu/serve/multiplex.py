"""Model multiplexing: many models served by few replicas, LRU-loaded.

Reference: ``python/ray/serve/api.py`` ``@serve.multiplexed`` +
``serve/_private/multiplex.py`` ``ModelMultiplexWrapper`` — a replica
holds up to ``max_num_models_per_replica`` models in an LRU cache; the
router keeps requests for one model id on the same replica so its cache
hits. TPU-native simplification: affinity comes from consistent hashing
of the model id over the replica set (the reference pushes loaded-model
reports through the controller; hashing gives the same steady-state
locality without the feedback loop).
"""

from __future__ import annotations

import contextvars
import functools
import inspect
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

_model_id_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Model id of the in-flight request (reference:
    ``serve.get_multiplexed_model_id``); "" outside a multiplexed call."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id)


def _reset_model_id(token) -> None:
    _model_id_ctx.reset(token)


def get_request_tenant() -> str:
    """The in-flight request's TENANT for telemetry attribution: the
    multiplexed model id ('' for single-tenant deployments). The
    ``ray_tpu_serve_request_*`` histograms carry this as their
    ``tenant`` tag so one noisy tenant's TTFT/TPOT is separable from
    the deployment aggregate. Delegates to
    :func:`get_multiplexed_model_id` — one source of truth if a
    default-tenant rule ever lands."""
    return get_multiplexed_model_id()


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate a model-loader method ``(self, model_id) -> model``: calls
    hit a per-replica LRU so at most ``max_num_models_per_replica`` models
    stay resident; older ones are evicted on overflow."""

    def decorate(loader: Callable):
        cache_attr = f"__serve_mux_cache_{loader.__name__}"

        def _cache(self) -> OrderedDict:
            cache = getattr(self, cache_attr, None)
            if cache is None:
                cache = OrderedDict()
                setattr(self, cache_attr, cache)
            return cache

        if inspect.iscoroutinefunction(loader):
            @functools.wraps(loader)
            async def wrapper(self, model_id: str):
                cache = _cache(self)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = await loader(self, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                return model
        else:
            @functools.wraps(loader)
            def wrapper(self, model_id: str):
                cache = _cache(self)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = loader(self, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                return model

        wrapper.__serve_multiplexed__ = True
        return wrapper

    if func is not None:
        return decorate(func)
    return decorate


# --------------------------------------------------- per-tenant rate limits
class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.
    ``try_acquire`` is non-blocking — on refusal it returns the seconds
    until the next token, which the ingress turns into a Retry-After
    header instead of queueing the request."""

    __slots__ = ("rate", "burst", "tokens", "ts")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        # A zero-rate bucket is a hard-disabled tenant: it must refuse
        # from the first request, not grant one burst token.
        self.tokens = self.burst if self.rate > 0 else 0.0
        self.ts = time.monotonic()

    def try_acquire(self, now: Optional[float] = None) -> Optional[float]:
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + (now - self.ts) * self.rate)
        self.ts = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        if self.rate <= 0:
            return 60.0          # hard-disabled tenant: long back-off
        return (1.0 - self.tokens) / self.rate


class TenantRateLimiter:
    """Per-tenant token buckets for the ingress admission gate (tenant =
    the multiplexed model id; '' is the anonymous tenant). Limits come
    from ``set_limit`` per tenant, falling back to the
    ``RAY_TPU_TENANT_RPS`` / ``RAY_TPU_TENANT_BURST`` env defaults
    (unset/0 RPS = unlimited). Rejections are tagged into
    ``ray_tpu_serve_request_outcomes_total`` by the gate."""

    def __init__(self):
        self._lock = threading.Lock()
        self._limits: Dict[str, Tuple[float, float]] = {}
        self._buckets: Dict[str, TokenBucket] = {}

    def set_limit(self, tenant: str, rps: float,
                  burst: Optional[float] = None) -> None:
        """Override one tenant's budget (rps <= 0 disables the tenant;
        burst defaults to max(rps, 1))."""
        with self._lock:
            self._limits[tenant] = (float(rps),
                                    float(burst) if burst is not None
                                    else max(float(rps), 1.0))
            self._buckets.pop(tenant, None)   # rebuild on next acquire

    def clear_limit(self, tenant: str) -> None:
        with self._lock:
            self._limits.pop(tenant, None)
            self._buckets.pop(tenant, None)

    def _default_limit(self) -> Optional[Tuple[float, float]]:
        rps = float(os.environ.get("RAY_TPU_TENANT_RPS", "0") or 0)
        if rps <= 0:
            return None          # unlimited by default
        burst = float(os.environ.get("RAY_TPU_TENANT_BURST", "0") or 0)
        return rps, (burst if burst > 0 else max(rps, 1.0))

    def try_acquire(self, tenant: str) -> Optional[float]:
        """None = admitted; else seconds until this tenant's next token
        (the Retry-After the ingress should advertise)."""
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                limit = self._limits.get(tenant)
                explicit = limit is not None
                if limit is None:
                    limit = self._default_limit()
                if limit is None:
                    return None  # unlimited tenant: no bucket at all
                if not explicit and limit[0] <= 0:
                    return None
                bucket = self._buckets[tenant] = TokenBucket(*limit)
            return bucket.try_acquire()


_rate_limiter: Optional[TenantRateLimiter] = None
_rate_limiter_lock = threading.Lock()


def tenant_rate_limiter() -> TenantRateLimiter:
    """Process-wide limiter shared by every ingress (HTTP + gRPC)."""
    global _rate_limiter
    with _rate_limiter_lock:
        if _rate_limiter is None:
            _rate_limiter = TenantRateLimiter()
        return _rate_limiter
