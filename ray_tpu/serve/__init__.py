"""ray_tpu.serve: scalable model serving (reference: ``python/ray/serve``)."""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    DeploymentHandle,
    DeploymentResponse,
    delete,
    deployment,
    drain,
    get_deployment_handle,
    get_role_group,
    register_role_group,
    run,
    shutdown,
    start_grpc,
    start_http,
    stop_grpc,
    stop_http,
    unregister_role_group,
)
from ray_tpu.serve.api import DeploymentResponseGenerator
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import (build_config, deploy_config_data,
                                  deploy_config_dict, deploy_config_file)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Application", "Deployment", "DeploymentHandle", "DeploymentResponse",
    "DeploymentResponseGenerator", "batch", "build_config", "delete",
    "deploy_config_data", "deploy_config_dict", "deploy_config_file",
    "deployment", "drain", "get_deployment_handle",
    "get_multiplexed_model_id", "get_role_group", "multiplexed",
    "register_role_group", "run", "shutdown",
    "start_grpc", "start_http", "stop_grpc", "stop_http",
    "unregister_role_group",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("serve")
del _rlu
