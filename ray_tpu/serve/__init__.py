"""ray_tpu.serve: scalable model serving (reference: ``python/ray/serve``)."""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    DeploymentHandle,
    DeploymentResponse,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start_http,
    stop_http,
)
from ray_tpu.serve.batching import batch

__all__ = [
    "Application", "Deployment", "DeploymentHandle", "DeploymentResponse",
    "batch", "delete", "deployment", "get_deployment_handle", "run",
    "shutdown", "start_http", "stop_http",
]
