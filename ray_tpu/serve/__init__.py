"""ray_tpu.serve: scalable model serving (reference: ``python/ray/serve``)."""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    DeploymentHandle,
    DeploymentResponse,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start_http,
    stop_http,
)
from ray_tpu.serve.api import DeploymentResponseGenerator
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Application", "Deployment", "DeploymentHandle", "DeploymentResponse",
    "DeploymentResponseGenerator", "batch", "delete", "deployment",
    "get_deployment_handle", "get_multiplexed_model_id", "multiplexed",
    "run", "shutdown", "start_http", "stop_http",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("serve")
del _rlu
