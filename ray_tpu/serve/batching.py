"""@serve.batch dynamic batching (reference: ``serve/batching.py``).

Concurrent calls to a batched method inside one replica are collected into a
list and executed together; each caller gets its own element of the returned
list. The replica must run with ``max_concurrency > 1`` so calls can overlap
(ray_tpu serve replicas default to 100, like the reference's async replicas).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.wait_timeout_s = wait_timeout_s
        self.lock = threading.Lock()
        self.items: List[Any] = []
        self.results: dict = {}
        self.done = threading.Condition(self.lock)
        self.leader_running = False

    def submit(self, instance, item) -> Any:
        my_id = object()
        with self.lock:
            self.items.append((my_id, item))
            am_leader = not self.leader_running
            if am_leader:
                self.leader_running = True
        if am_leader:
            # Drain batches until the queue is empty, then hand off leadership.
            while True:
                time.sleep(self.wait_timeout_s)  # let followers enqueue
                with self.lock:
                    batch = self.items[: self.max_batch_size]
                    self.items = self.items[self.max_batch_size:]
                if not batch:
                    with self.lock:
                        self.leader_running = False
                        self.done.notify_all()
                    break
                ids = [i for i, _ in batch]
                args = [a for _, a in batch]
                try:
                    outs = self.fn(instance, args)
                    if len(outs) != len(args):
                        raise ValueError(
                            f"@serve.batch function returned {len(outs)} "
                            f"results for {len(args)} inputs")
                except BaseException as e:  # noqa: BLE001
                    outs = [e] * len(args)
                with self.lock:
                    for i, out in zip(ids, outs):
                        self.results[i] = out
                    self.done.notify_all()
                    if not self.items:
                        self.leader_running = False
                        break
        with self.lock:
            deadline = time.monotonic() + 60.0
            while my_id not in self.results:
                if not self.leader_running and any(
                        i == my_id for i, _ in self.items):
                    # Leader exited between our enqueue and its drain: take over.
                    self.leader_running = True
                    self.lock.release()
                    try:
                        return self._lead_for_self(instance, my_id)
                    finally:
                        self.lock.acquire()
                self.done.wait(timeout=0.1)
                if time.monotonic() > deadline:
                    raise TimeoutError("batched call never executed")
            result = self.results.pop(my_id)
        if isinstance(result, BaseException):
            raise result
        return result

    def _lead_for_self(self, instance, my_id):
        while True:
            with self.lock:
                batch = self.items[: self.max_batch_size]
                self.items = self.items[self.max_batch_size:]
                if not batch:
                    self.leader_running = False
                    result = self.results.pop(my_id, None)
            if not batch:
                if isinstance(result, BaseException):
                    raise result
                return result
            ids = [i for i, _ in batch]
            args = [a for _, a in batch]
            try:
                outs = self.fn(instance, args)
            except BaseException as e:  # noqa: BLE001
                outs = [e] * len(args)
            with self.lock:
                for i, out in zip(ids, outs):
                    self.results[i] = out
                self.done.notify_all()
                if my_id in self.results and not self.items:
                    self.leader_running = False
                    result = self.results.pop(my_id)
                    if isinstance(result, BaseException):
                        raise result
                    return result


def batch(_fn: Callable = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped method receives a LIST of inputs and must
    return a list of outputs of the same length."""

    def decorate(fn):
        queue_attr = f"__batch_queue_{fn.__name__}"
        params = (max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        def wrapper(self, item):
            q = getattr(self, queue_attr, None)
            if q is None:
                q = _BatchQueue(fn, *params)
                setattr(self, queue_attr, q)
            return q.submit(self, item)

        wrapper.__is_serve_batched__ = True
        return wrapper

    if _fn is not None:
        return decorate(_fn)
    return decorate
