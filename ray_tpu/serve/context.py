"""Serve request context: the per-request identity that rides the call.

Reference: ``ray.serve.context._serve_request_context`` — the reference
threads a ``RequestContext`` (request id, route, multiplexed model id)
through a ContextVar so replica user code can attribute work to the
in-flight request. Here the context also carries the TRACE linkage
(trace id + parent span id minted at ingress/route), which is how the
continuous-batching engine connects its lifecycle spans — emitted from
its own tick thread, long after the handler returned — to the request's
trace.

A ContextVar (not a thread-local) because the replica runs sync user
code in executor threads via ``contextvars.copy_context().run`` — the
copied context carries this across the thread hop, exactly like the
multiplexed model id.
"""

from __future__ import annotations

import contextvars
from typing import Any, Dict, Optional

_request_ctx: contextvars.ContextVar[Optional[Dict[str, Any]]] = \
    contextvars.ContextVar("serve_request_context", default=None)


def get_request_context() -> Optional[Dict[str, Any]]:
    """The in-flight serve request's context, or None outside a serve
    call. Keys: ``request_id``, ``trace_id``, ``parent_span_id``,
    ``deployment``, ``tenant`` (the multiplexed model id, '' for
    single-tenant deployments), and — on a request the recovery journal
    re-dispatched after replica death or a drain reject — ``attempt``
    (1-based redispatch count; absent on the first attempt). The ids
    stay IDENTICAL across attempts: a resumed request is one trace whose
    engine spans land on two replicas."""
    return _request_ctx.get()


def get_request_attempt() -> int:
    """Redispatch count of the in-flight request (0 = first attempt —
    also outside any serve call)."""
    ctx = _request_ctx.get()
    return int(ctx.get("attempt", 0)) if ctx else 0


def _set_request_context(ctx: Optional[Dict[str, Any]]):
    return _request_ctx.set(ctx)


def _reset_request_context(token) -> None:
    _request_ctx.reset(token)


__all__ = ["get_request_attempt", "get_request_context"]
