"""Serve in-flight request recovery: the journal + resume plane.

The serve twin of ``train/elastic.py``'s restart machinery: a replica
death must not cost the caller their request. Every streaming request
dispatched through the ingress router is journaled — its *immutable
submission* (the payload: prompt token ids, sampling knobs, max_tokens),
the tenant, the request's trace context, and the items already streamed
to the caller. When the serving replica dies mid-flight
(``ActorDiedError`` surfacing out of the response stream), the journal
decides the recovery:

* **queued or prefilling** (zero items streamed): the submission is
  simply resubmitted to a live replica — nothing was delivered, so the
  retry is invisible (``cause="resubmit"``).
* **mid-decode** (tokens already streamed): the journal rebuilds the
  request as ``prompt + already-emitted tokens`` with the remaining
  token budget and replays it as a fresh prefill on a live replica
  (``cause="resume"``). Under greedy decoding this is **exactly-once by
  construction**: the next token is a pure function of the context, so
  the resumed stream continues bit-identically (verified by the chaos
  e2e tests). A *sampled* request re-seeds at the resume point — its
  continuation is a fresh draw, surfaced to the client via the
  ``x-ray-tpu-resumed`` marker so exactly-once consumers can tell.
* **draining replica** (clean reject at dispatch,
  ``ReplicaDrainingError``): re-routed to another replica without
  consuming the resume budget — the replica did no work.

Budget: ``RAY_TPU_SERVE_MAX_RESUMES`` (default 2) death recoveries per
request; exhaustion raises the typed
:class:`~ray_tpu.exceptions.ResumeExhaustedError` and tags the request
``resume_exhausted`` in ``ray_tpu_serve_request_outcomes_total``. A
stream that completes after >=1 recovery is tagged ``resumed``.

Every router dispatch path (unary retry in
``serve/api.py::DeploymentResponse.result`` and the streaming path here)
handles ``ActorDiedError`` through this module — a tier-1 source lint
(tests/test_metrics_lint.py) enforces that no bare retry creeps back in.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu import exceptions
from ray_tpu._private import events as _events

logger = logging.getLogger(__name__)


def _flight_resume(j: "RequestJournal", mode: str) -> str:
    """Flight-recorder record of one stream re-route/resume. The
    request id comes from the trace context when present; otherwise one
    is minted at the first recovery and stuck to the journal, so every
    recovery of the request chains under the same subject. The cause is
    inferred best-effort from the in-process ring (newest drain-begin
    for this deployment, else newest drain/injection anywhere)."""
    rid = (j.request_ctx or {}).get("request_id", "")
    if not rid:
        rid = getattr(j, "flight_request_id", "")
        if not rid:
            rid = j.flight_request_id = uuid.uuid4().hex[:16]
    cause = _events.latest_event_id(
        ["serve.drain_begin"], subject={"deployment": j.deployment}) or \
        _events.latest_event_id(["serve.drain_begin", "chaos.inject"])
    return _events.emit(
        "serve.resume", cause=cause,
        subject={"deployment": j.deployment, "request_id": rid},
        mode=mode, emitted=len(j.emitted), attempt=j.resumes)

#: Stream/header marker a client sees when a SAMPLED request was resumed
#: mid-decode (its continuation re-seeded — not the draw the dead
#: replica would have produced). Greedy resumes are exactly-once and
#: carry no marker.
RESUMED_MARKER = "x-ray-tpu-resumed"

#: Sentinel from :meth:`RequestJournal.resume_payload`: every requested
#: token was already delivered before the death — the stream is complete,
#: nothing to resume.
COMPLETE = object()


def max_resumes() -> int:
    """Per-request death-recovery budget (``RAY_TPU_SERVE_MAX_RESUMES``,
    read per decision so tests/operators retune live)."""
    return int(os.environ.get("RAY_TPU_SERVE_MAX_RESUMES", "2"))


#: Drain rejects a single request tolerates before giving up — rejects
#: are free (the replica did no work) but must be bounded so a
#: deployment whose every replica is draining cannot spin a dispatch
#: loop forever. Shared by the streaming journal and the unary path in
#: ``serve/api.py`` (ONE policy, no drift).
DRAIN_REJECT_CAP = 16


def exhausted_error(deployment: str,
                    resumes: int) -> "exceptions.ResumeExhaustedError":
    """The one typed terminal error both dispatch paths raise when the
    resume budget runs out."""
    return exceptions.ResumeExhaustedError(
        f"replica serving {deployment!r} died and the resume budget "
        f"(RAY_TPU_SERVE_MAX_RESUMES={max_resumes()}) is spent",
        resumes=resumes)


def is_llm_payload(payload: Any) -> bool:
    """True for the LLM completion payload shape (``prompt_token_ids``)
    whose streams are token-id items — the only shape resumable
    *mid-stream* (the emitted tokens extend the prompt)."""
    return (isinstance(payload, dict)
            and isinstance(payload.get("prompt_token_ids"), (list, tuple)))


def is_sampled(payload: Any) -> bool:
    """True when the request explicitly asks for sampled decoding —
    the case whose mid-decode resume re-seeds (and gets the
    ``x-ray-tpu-resumed`` marker). Engine-default decoding is greedy
    argmax, so an unannotated payload counts as greedy."""
    if not isinstance(payload, dict):
        return False
    try:
        if float(payload.get("temperature") or 0.0) > 0.0:
            return True
    except (TypeError, ValueError):
        return True  # unparseable knob: assume sampled (be honest)
    s = payload.get("sampling")
    if isinstance(s, dict):
        try:
            return float(s.get("temperature") or 0.0) > 0.0
        except (TypeError, ValueError):
            return True
    return False


class RequestJournal:
    """The immutable submission + delivery ledger of ONE streaming
    request. The payload is never mutated; resume payloads are derived
    copies. ``emitted`` holds exactly the items the consumer has been
    handed (recorded *after* a successful pull, so an item lost in
    flight is replayed, never skipped)."""

    def __init__(self, deployment: str, method: Optional[str],
                 payload: Any, model_id: str = "",
                 request_ctx: Optional[Dict[str, Any]] = None):
        self.deployment = deployment
        self.method = method
        self.payload = payload
        self.model_id = model_id
        # The SAME request context rides every attempt, so a resumed
        # request's spans across two replicas land in ONE trace
        # (`ray-tpu trace request` shows both replicas' engine spans).
        self.request_ctx = request_ctx
        self.emitted: List[Any] = []
        self.resumes = 0          # death recoveries (budgeted)
        self.drain_rejects = 0    # clean re-routes (not budgeted)
        self.resumed_midstream = False
        # Disaggregated prefill/decode: one ledger entry per KV handoff
        # the router committed on this request's behalf (crc32, bytes,
        # attempt). Exactly-once billing hangs off this list — a clean
        # split request journals EXACTLY ONE handoff, and a decode death
        # after the noted handoff recovers as a "resume" (the first
        # token crossed replicas) rather than an invisible resubmit.
        self.handoffs: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ queries
    @property
    def llm(self) -> bool:
        return is_llm_payload(self.payload)

    @property
    def sampled(self) -> bool:
        return is_sampled(self.payload)

    @property
    def needs_marker(self) -> bool:
        """The client must be told: a sampled request was resumed
        mid-decode, so its continuation is a re-seeded draw."""
        return self.resumed_midstream and self.sampled

    def record(self, item: Any) -> None:
        self.emitted.append(item)

    def note_handoff(self, meta: Dict[str, Any]) -> Dict[str, Any]:
        """Journal one prefill→decode KV handoff — idempotent PER
        ATTEMPT, so a retried bookkeeping call cannot double-bill the
        transfer (the double-billing regression asserts a clean split
        request ends with exactly one ledger entry). The entry is the
        manifest's billing-relevant core: crc32, byte/block counts, and
        the attempt that shipped it."""
        attempt = int(meta.get("attempt", self.resumes))
        for entry in self.handoffs:
            if entry.get("attempt") == attempt:
                return entry
        entry = {**meta, "attempt": attempt}
        self.handoffs.append(entry)
        return entry

    def tags(self, engine: str = "router") -> Dict[str, str]:
        return {"deployment": self.deployment, "tenant": self.model_id,
                "engine": engine}

    # ------------------------------------------------------------- resume
    def resume_payload(self) -> Any:
        """The next attempt's submission, derived from the journal:

        * nothing emitted -> the original payload (plain resubmission);
        * mid-stream LLM request -> prompt extended by the emitted
          tokens, ``max_tokens`` reduced by them (:data:`COMPLETE` when
          zero remain);
        * mid-stream non-LLM request -> ``None`` (items already reached
          the caller and the stream has no replay semantics — not
          resumable)."""
        if not self.emitted:
            return self.payload
        if not self.llm:
            return None
        toks: List[int] = []
        for it in self.emitted:
            if isinstance(it, bool) or not isinstance(it, int):
                return None  # non-token items: no replay semantics
            toks.append(int(it))
        try:
            budget = int(self.payload.get("max_tokens", 16))
        except (TypeError, ValueError):
            return None
        remaining = budget - len(toks)
        if remaining <= 0:
            return COMPLETE
        ids = list(self.payload["prompt_token_ids"]) + toks
        # resumed_tokens marks this as a mid-decode REPLAY: the serving
        # deployment uses it to honor an EOS that was already streamed
        # (the generation had finished; only the end-of-stream sentinel
        # was lost with the replica) instead of decoding past it with
        # the leftover budget.
        return {**self.payload, "prompt_token_ids": ids,
                "max_tokens": remaining, "resumed_tokens": len(toks)}


class RecoverableStream:
    """Iterator over a streaming deployment call that survives replica
    death and drain. Wraps the handle dispatch: every pull that raises
    ``ActorDiedError`` goes through the journal (resubmit / resume /
    typed exhaustion), and a ``ReplicaDrainingError`` reject re-routes
    to a live replica for free. This is the ONLY place the streaming
    router path handles ``ActorDiedError`` (source-linted)."""

    def __init__(self, handle, journal: RequestJournal,
                 per_item_timeout_s: Optional[float] = 60.0):
        self._handle = handle
        self.journal = journal
        self._timeout = per_item_timeout_s
        self._inner = None
        self._replica = None
        self._completion_reported = False

    def __iter__(self) -> "RecoverableStream":
        return self

    # ---------------------------------------------------------- dispatch
    def _dispatch(self, payload: Any) -> None:
        from ray_tpu.serve.proxy import prefix_fingerprint

        j = self.journal
        # The SAME trace context rides every attempt (one trace across
        # both replicas); the attempt counter is stamped in so the two
        # replicas' engine spans are tell-apart-able in the transcript.
        rctx = j.request_ctx
        if rctx is not None and (j.resumes or j.drain_rejects):
            rctx = {**rctx, "attempt": j.resumes + j.drain_rejects}
        # The prefix key is recomputed from the attempt's payload: after
        # an eviction the rendezvous ring has one fewer replica, so the
        # key re-homes onto the dead replica's second choice.
        h = self._handle.options(
            j.method, stream=True, multiplexed_model_id=j.model_id,
            request_context=rctx,
            prefix_key=prefix_fingerprint(payload))
        gen = h.remote(payload)
        gen._timeout = self._timeout
        self._replica = getattr(gen, "_replica", None)
        self._inner = iter(gen)

    def _evict(self) -> None:
        if self._replica is not None:
            try:
                self._handle._evict(self._replica)
            except Exception:  # noqa: BLE001 — eviction is best-effort
                pass
            self._replica = None

    def _death_cause(self) -> str:
        """Recovery tag for a replica death: "resume" once items reached
        the caller, else an invisible "resubmit". The disaggregated
        stream overrides this — a decode death after the journaled
        handoff is a resume even before the first token streamed."""
        return "resume" if self.journal.emitted else "resubmit"

    # ------------------------------------------------------------ recover
    def _reroute_drained(self) -> None:
        """The chosen replica is draining (clean reject — it did no
        work): evict it locally and redispatch the same submission.
        Free — no resume budget consumed — but bounded by the replica
        count so a fully-draining deployment cannot spin forever."""
        from ray_tpu._private import metrics_defs as mdefs

        j = self.journal
        j.drain_rejects += 1
        if j.drain_rejects > DRAIN_REJECT_CAP:
            raise exceptions.ReplicaDrainingError(
                f"every replica of {j.deployment!r} rejected the request "
                f"as draining ({j.drain_rejects} rejects)")
        self._evict()
        mdefs.SERVE_REPLICA_RESUMES.inc(tags={
            "deployment": j.deployment, "cause": "drain_reject"})
        _flight_resume(j, "drain_reject")
        # A drain reject happens at dispatch, before anything streamed,
        # so the original submission redispatches verbatim.
        self._dispatch(j.resume_payload() if j.emitted else j.payload)

    def _resume_after_death(self, err: BaseException) -> None:
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.util import tracing

        j = self.journal
        self._evict()
        payload = j.resume_payload()
        if payload is None:
            # Items already reached the caller and the stream has no
            # replay semantics: recovery would duplicate or reorder
            # delivered items, so surface the death honestly.
            raise err
        if payload is COMPLETE:
            # Every requested token was delivered before the death: the
            # stream is COMPLETE, not failed (only the end-of-stream
            # notification was lost) — no budget consumed, so this
            # check precedes the exhaustion gate.
            self._inner = iter(())
            return
        if j.resumes >= max_resumes():
            mdefs.SERVE_REQ_OUTCOMES.inc(tags={
                **j.tags(), "outcome": "resume_exhausted"})
            raise exhausted_error(j.deployment, j.resumes) from err
        cause = self._death_cause()
        j.resumes += 1
        if j.emitted:
            j.resumed_midstream = True
        mdefs.SERVE_REPLICA_RESUMES.inc(tags={
            "deployment": j.deployment, "cause": cause})
        _flight_resume(j, cause)
        rctx = j.request_ctx or {}
        if rctx and tracing.enabled():
            # A zero-duration marker span in the request's trace: the
            # recovery point between the two replicas' engine spans.
            tracing.emit_span(
                "serve.resume", trace_id=rctx.get("trace_id", ""),
                parent_span_id=rctx.get("parent_span_id", ""),
                ts=time.time(), dur=0.0, kind="route",
                request_id=rctx.get("request_id", ""),
                deployment=j.deployment, cause=cause,
                emitted=len(j.emitted), attempt=j.resumes)
        logger.warning(
            "serve: %s request to %r after replica death "
            "(%d item(s) already streamed, attempt %d/%d)",
            cause, j.deployment, len(j.emitted), j.resumes,
            max_resumes())
        self._dispatch(payload)

    # ------------------------------------------------------------ iterate
    def __next__(self) -> Any:
        from ray_tpu._private import metrics_defs as mdefs

        j = self.journal
        if self._inner is None:
            self._dispatch(j.payload)
        while True:
            try:
                item = next(self._inner)
            except StopIteration:
                if j.resumes and not self._completion_reported:
                    self._completion_reported = True
                    mdefs.SERVE_REQ_OUTCOMES.inc(tags={
                        **j.tags(), "outcome": "resumed"})
                raise
            except exceptions.ReplicaDrainingError:
                self._reroute_drained()
                continue
            except exceptions.ActorDiedError as e:
                self._resume_after_death(e)
                continue
            j.record(item)
            return item


class DisaggRecoverableStream(RecoverableStream):
    """Recoverable stream over a (prefill, decode) ROLE-GROUP pair —
    the disaggregated twin of :class:`RecoverableStream`. Dispatch is
    staged: pre-reserve the decode slot, run the unary ``prefill`` on
    the prefill group (it returns the KV handoff manifest; the staging
    bytes ride the shm channel named inside it), journal the handoff,
    then open the ``decode_from`` stream on the decode group. Every
    token — including the prefill-produced first one — reaches the
    caller only through the decode stream, so the journal's ``emitted``
    ledger stays the single source of delivery truth.

    Death on either side lands in the SAME journal:

    * **prefill death** (unary — nothing delivered, nothing journaled):
      the submission resubmits verbatim to another prefill replica
      (``cause="resubmit"``, budgeted like any death retry);
    * **decode death after the handoff**: replay from the journal as a
      fresh prefill wherever capacity exists (``cause="resume"`` — the
      first token crossed replicas, so the recovery is visible state,
      not an invisible reroute). The journaled handoff means the
      request is never billed twice: the replay journals a NEW attempt
      entry, and :meth:`RequestJournal.note_handoff` refuses duplicate
      entries for the same attempt.

    This class is the only place the disaggregated router path handles
    ``ActorDiedError`` (the same source lint that pins the colocated
    path to this module covers it)."""

    def __init__(self, prefill_handle, decode_handle,
                 journal: RequestJournal,
                 per_item_timeout_s: Optional[float] = 60.0):
        super().__init__(decode_handle, journal, per_item_timeout_s)
        self._prefill_handle = prefill_handle
        # True between note_handoff and clean stream end: a death in
        # that window is a decode death AFTER the handoff.
        self._handoff_live = False

    def _death_cause(self) -> str:
        return ("resume" if (self.journal.emitted or self._handoff_live)
                else "resubmit")

    def _resume_after_death(self, err: BaseException) -> None:
        from ray_tpu._private import metrics_defs as mdefs

        handoff_was_live = self._handoff_live
        if handoff_was_live:
            mdefs.SERVE_HANDOFFS.inc(tags={
                "deployment": self.journal.deployment,
                "outcome": "decode_died"})
        pre = self.journal.resumes
        super()._resume_after_death(err)
        if handoff_was_live and self.journal.resumes > pre:
            # The death post-dates a journaled handoff: the first token
            # crossed replicas, so even with zero tokens DELIVERED the
            # replay is visible state — a sampled request must carry
            # the resumed marker to the client.
            self.journal.resumed_midstream = True

    # ---------------------------------------------------------- dispatch
    def _prefill_attempt(self, payload: Any, rctx, fp: str):
        """One journaled prefill attempt: returns the manifest, or None
        when the chosen prefill replica died/drained (the journal was
        advanced and the caller retries)."""
        import ray_tpu
        from ray_tpu._private import metrics_defs as mdefs

        j = self.journal
        h = self._prefill_handle.options(
            "prefill", multiplexed_model_id=j.model_id,
            request_context=rctx, prefix_key=fp)
        resp = h.remote(payload)
        try:
            return ray_tpu.get(resp._ref, timeout=self._timeout)
        except exceptions.ReplicaDrainingError:
            # Clean reject — free reroute, bounded by the shared cap.
            j.drain_rejects += 1
            if j.drain_rejects > DRAIN_REJECT_CAP:
                raise exceptions.ReplicaDrainingError(
                    f"every prefill replica of {j.deployment!r} rejected "
                    f"the request as draining ({j.drain_rejects} rejects)")
            try:
                self._prefill_handle._evict(resp._replica)
            except Exception:  # noqa: BLE001 — eviction is best-effort
                pass
            mdefs.SERVE_REPLICA_RESUMES.inc(tags={
                "deployment": j.deployment, "cause": "drain_reject"})
            _flight_resume(j, "drain_reject")
            return None
        except exceptions.ActorDiedError as e:
            # Prefill death: ZERO bytes reached the caller and no
            # handoff was journaled, so the immutable submission
            # resubmits to another prefill replica — budgeted.
            try:
                self._prefill_handle._evict(resp._replica)
            except Exception:  # noqa: BLE001
                pass
            mdefs.SERVE_HANDOFFS.inc(tags={
                "deployment": j.deployment, "outcome": "prefill_died"})
            if j.resumes >= max_resumes():
                mdefs.SERVE_REQ_OUTCOMES.inc(tags={
                    **j.tags(), "outcome": "resume_exhausted"})
                raise exhausted_error(j.deployment, j.resumes) from e
            j.resumes += 1
            mdefs.SERVE_REPLICA_RESUMES.inc(tags={
                "deployment": j.deployment, "cause": "resubmit"})
            _flight_resume(j, "resubmit")
            logger.warning(
                "serve: resubmitting prefill for %r after replica death "
                "(attempt %d/%d)", j.deployment, j.resumes, max_resumes())
            return None

    def _dispatch(self, payload: Any) -> None:
        import ray_tpu
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.serve.proxy import prefix_fingerprint

        j = self.journal
        self._handoff_live = False
        fp = prefix_fingerprint(payload)
        prompt = (payload.get("prompt_token_ids") or ()
                  if isinstance(payload, dict) else ())
        try:
            budget = int(payload.get("max_tokens", 16)) \
                if isinstance(payload, dict) else 16
        except (TypeError, ValueError):
            budget = 16
        # (1) PRE-RESERVE the decode slot before any prefill work: the
        # payload must never race arena pressure on arrival. Best-effort
        # — a miss (arena full, replica mismatch) just means the import
        # allocates on arrival; the replica-nonce inside the ticket
        # keeps a ticket from one decode replica from being spent on
        # another, and unspent tickets expire engine-side (TTL).
        reservation = None
        try:
            reservation = ray_tpu.get(
                self._handle.options(
                    "reserve_kv", multiplexed_model_id=j.model_id,
                    prefix_key=fp).remote(len(prompt), budget)._ref,
                timeout=5)
        except Exception:  # noqa: BLE001 — reservation is advisory
            reservation = None
        # (2) PREFILL (journaled unary retry loop).
        while True:
            rctx = j.request_ctx
            if rctx is not None and (j.resumes or j.drain_rejects):
                rctx = {**rctx, "attempt": j.resumes + j.drain_rejects}
            manifest = self._prefill_attempt(payload, rctx, fp)
            if manifest is not None:
                break
        if isinstance(manifest, dict) and "done" in manifest:
            # The request finished entirely at prefill (max_tokens == 1,
            # EOS at the first token, or a resumed prompt already ending
            # in EOS): nothing to hand off — the completed tokens stream
            # straight out and are journaled like any other items.
            self._replica = None
            self._inner = iter(list(manifest["done"]))
            return
        # (3) JOURNAL the handoff before the decode side can touch it:
        # the manifest only becomes importable once stamped (the
        # transfer helper refuses unstamped manifests), so a request
        # can never be billed for an un-journaled transfer.
        j.note_handoff({
            "crc32": manifest.get("crc32"),
            "nbytes": manifest.get("nbytes"),
            "num_blocks": manifest.get("num_blocks"),
            "attempt": j.resumes,
        })
        manifest = {**manifest, "journaled": True}
        self._handoff_live = True
        # (4) DECODE stream: every token (first included) arrives here.
        dh = self._handle.options(
            "decode_from", stream=True, multiplexed_model_id=j.model_id,
            request_context=rctx, prefix_key=fp)
        gen = dh.remote({"manifest": manifest,
                         "reservation": reservation})
        gen._timeout = self._timeout
        self._replica = getattr(gen, "_replica", None)
        self._inner = iter(gen)
        mdefs.SERVE_HANDOFFS.inc(tags={
            "deployment": j.deployment, "outcome": "ok"})


def note_unary_resumed(deployment: str, tenant: str) -> None:
    """Metrics for a unary call that completed after >=1 death retry
    (the ``serve/api.py`` unary journal path)."""
    from ray_tpu._private import metrics_defs as mdefs

    mdefs.SERVE_REQ_OUTCOMES.inc(tags={
        "deployment": deployment, "tenant": tenant, "engine": "router",
        "outcome": "resumed"})


def note_unary_exhausted(deployment: str, tenant: str) -> None:
    from ray_tpu._private import metrics_defs as mdefs

    mdefs.SERVE_REQ_OUTCOMES.inc(tags={
        "deployment": deployment, "tenant": tenant, "engine": "router",
        "outcome": "resume_exhausted"})


def note_unary_retry(deployment: str, cause: str) -> None:
    from ray_tpu._private import metrics_defs as mdefs

    mdefs.SERVE_REPLICA_RESUMES.inc(tags={
        "deployment": deployment, "cause": cause})


__all__ = ["COMPLETE", "DRAIN_REJECT_CAP", "DisaggRecoverableStream",
           "RESUMED_MARKER",
           "RecoverableStream", "RequestJournal", "exhausted_error",
           "is_llm_payload", "is_sampled", "max_resumes",
           "note_unary_exhausted", "note_unary_resumed",
           "note_unary_retry"]
