"""Dashboard subprocess entry (used by ``ray-tpu up``)."""

import argparse
import time

from ray_tpu.dashboard import Dashboard


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--gcs-address", required=True)
    p.add_argument("--port", type=int, default=8265)
    args = p.parse_args(argv)
    dash = Dashboard(args.gcs_address, port=args.port)
    print(f"DASHBOARD_PORT={dash.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dash.stop()


if __name__ == "__main__":
    main()
