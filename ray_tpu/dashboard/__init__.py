"""Minimal dashboard: JSON endpoints + Prometheus metrics.

Reference: ``python/ray/dashboard`` (head.py:65 aiohttp app + modules). The
React frontend is out of scope; the API surface the CLI/users consume is
here: ``/api/cluster_status``, ``/api/nodes``, ``/api/actors``,
``/api/jobs``, ``/metrics`` (Prometheus text).
"""

from __future__ import annotations

import json
import pickle
import threading
import time
from collections import deque
from typing import Optional


class Dashboard:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 8265):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ray_tpu._private import rpc
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        gcs = rpc.get_stub("GcsService", gcs_address)

        def nodes():
            return [{
                "node_id": n.node_id, "address": n.address, "alive": n.alive,
                "resources": dict(n.resources), "available": dict(n.available),
                "labels": dict(n.labels),
            } for n in gcs.GetNodes(pb.GetNodesRequest()).nodes]

        def actors():
            return [{
                "actor_id": a.actor_id.hex(), "class_name": a.class_name,
                "state": a.state, "name": a.name, "node_id": a.node_id,
                "num_restarts": a.num_restarts,
            } for a in gcs.ListActors(
                pb.ListActorsRequest(all_namespaces=True)).actors]

        def jobs():
            keys = gcs.KvKeys(pb.KvRequest(ns="job", prefix="")).keys
            out = []
            for k in keys:
                r = gcs.KvGet(pb.KvRequest(ns="job", key=k))
                if r.found:
                    out.append(json.loads(r.value))
            return out

        log_buffer: deque = deque(maxlen=2000)

        def _log_subscriber():
            # The dashboard tails worker logs off the LOG pubsub channel
            # into a ring buffer for /api/logs (reference: dashboard log
            # viewing over the log agents).
            while True:
                try:
                    stream = gcs.Subscribe(pb.SubscribeRequest(
                        channels=["LOG"], subscriber_id="dashboard"))
                    for msg in stream:
                        try:
                            rec = pickle.loads(msg.data)
                            for line in rec.get("lines", ()):
                                log_buffer.append({
                                    "worker": rec.get("name", "?"),
                                    "pid": rec.get("pid"),
                                    "stream": rec.get("stream"),
                                    "line": line})
                        except Exception:  # noqa: BLE001
                            pass
                except Exception:  # noqa: BLE001
                    pass
                # Streams can also end CLEANLY (GCS stopping/restarting);
                # always back off before re-subscribing.
                time.sleep(1.0)

        threading.Thread(target=_log_subscriber, daemon=True).start()

        def logs():
            return list(log_buffer)

        def tasks():
            reply = gcs.KvGet(pb.KvRequest(ns="__task_events__",
                                           key="recent"))
            return pickle.loads(reply.value) if reply.found else []

        def cluster_status():
            ns = nodes()
            total, avail = {}, {}
            for n in ns:
                if not n["alive"]:
                    continue
                for k, v in n["resources"].items():
                    total[k] = total.get(k, 0) + v
                for k, v in n["available"].items():
                    avail[k] = avail.get(k, 0) + v
            return {"nodes_alive": sum(n["alive"] for n in ns),
                    "nodes_total": len(ns),
                    "resources_total": total, "resources_available": avail}

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                try:
                    if self.path == "/metrics":
                        from ray_tpu.util.metrics import prometheus_text

                        body = prometheus_text().encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        route = {
                            "/api/cluster_status": cluster_status,
                            "/api/nodes": nodes,
                            "/api/actors": actors,
                            "/api/jobs": jobs,
                            "/api/logs": logs,
                            "/api/tasks": tasks,
                        }.get(self.path)
                        if route is None:
                            self.send_response(404)
                            self.end_headers()
                            return
                        body = json.dumps(route()).encode()
                        ctype = "application/json"
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    body = json.dumps({"error": str(e)}).encode()
                    ctype = "application/json"
                    self.send_response(500)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def stop(self):
        self.server.shutdown()
