"""Minimal dashboard: JSON endpoints + Prometheus metrics.

Reference: ``python/ray/dashboard`` (head.py:65 aiohttp app + modules). The
React frontend is out of scope; the API surface the CLI/users consume is
here: ``/api/cluster_status``, ``/api/nodes``, ``/api/actors``,
``/api/jobs``, ``/metrics`` (Prometheus text).
"""

from __future__ import annotations

import json
import threading
from typing import Optional


class Dashboard:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 8265):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ray_tpu._private import rpc
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        gcs = rpc.get_stub("GcsService", gcs_address)

        def nodes():
            return [{
                "node_id": n.node_id, "address": n.address, "alive": n.alive,
                "resources": dict(n.resources), "available": dict(n.available),
                "labels": dict(n.labels),
            } for n in gcs.GetNodes(pb.GetNodesRequest()).nodes]

        def actors():
            return [{
                "actor_id": a.actor_id.hex(), "class_name": a.class_name,
                "state": a.state, "name": a.name, "node_id": a.node_id,
                "num_restarts": a.num_restarts,
            } for a in gcs.ListActors(
                pb.ListActorsRequest(all_namespaces=True)).actors]

        def jobs():
            keys = gcs.KvKeys(pb.KvRequest(ns="job", prefix="")).keys
            out = []
            for k in keys:
                r = gcs.KvGet(pb.KvRequest(ns="job", key=k))
                if r.found:
                    out.append(json.loads(r.value))
            return out

        def cluster_status():
            ns = nodes()
            total, avail = {}, {}
            for n in ns:
                if not n["alive"]:
                    continue
                for k, v in n["resources"].items():
                    total[k] = total.get(k, 0) + v
                for k, v in n["available"].items():
                    avail[k] = avail.get(k, 0) + v
            return {"nodes_alive": sum(n["alive"] for n in ns),
                    "nodes_total": len(ns),
                    "resources_total": total, "resources_available": avail}

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                try:
                    if self.path == "/metrics":
                        from ray_tpu.util.metrics import prometheus_text

                        body = prometheus_text().encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        route = {
                            "/api/cluster_status": cluster_status,
                            "/api/nodes": nodes,
                            "/api/actors": actors,
                            "/api/jobs": jobs,
                        }.get(self.path)
                        if route is None:
                            self.send_response(404)
                            self.end_headers()
                            return
                        body = json.dumps(route()).encode()
                        ctype = "application/json"
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    body = json.dumps({"error": str(e)}).encode()
                    ctype = "application/json"
                    self.send_response(500)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def stop(self):
        self.server.shutdown()
