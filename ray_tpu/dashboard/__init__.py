"""Minimal dashboard: JSON endpoints + Prometheus metrics.

Reference: ``python/ray/dashboard`` (head.py:65 aiohttp app + modules). The
React frontend is out of scope; the API surface the CLI/users consume is
here: ``/api/cluster_status``, ``/api/nodes``, ``/api/actors``,
``/api/jobs``, ``/metrics`` (Prometheus text).
"""

from __future__ import annotations

import json
import pickle
import threading
import time
from collections import deque
from typing import Optional


def _label_series(text: str, key: str, value: str) -> str:
    """Inject ``key="value"`` into every sample line of a Prometheus text
    exposition (comment/TYPE lines pass through) so aggregated scrapes
    stay distinguishable per node. Labeled lines split at the CLOSING
    brace (label values may contain spaces); bare names split at the
    first space (metric names cannot)."""
    out = []
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            out.append(line)
            continue
        if "{" in stripped:
            close = stripped.rfind("}")
            if close < 0:
                out.append(line)  # malformed: pass through untouched
                continue
            name, _, labels = stripped[:close].partition("{")
            rest = stripped[close + 1:].strip()
            sep = "," if labels else ""
            out.append(f'{name}{{{labels}{sep}{key}="{value}"}} {rest}')
        else:
            name_part, _, rest = stripped.partition(" ")
            out.append(f'{name_part}{{{key}="{value}"}} {rest}')
    return "\n".join(out)


def _merge_expositions(parts) -> str:
    """Concatenate Prometheus expositions keeping only the FIRST
    ``# TYPE``/``# HELP`` line per metric name — the text parser rejects
    a second TYPE line for the same name, and every process emits the
    same registry metadata."""
    seen = set()
    out = []
    for part in parts:
        for line in part.splitlines():
            stripped = line.strip()
            if stripped.startswith(("# TYPE ", "# HELP ")):
                words = stripped.split()  # ["#", "TYPE", name, ...]
                key = (words[1], words[2] if len(words) > 2 else "")
                if key in seen:
                    continue
                seen.add(key)
            out.append(line)
    return "\n".join(line for line in out if line.strip()) + "\n"


# Single-file frontend (reference: dashboard/client React app, condensed to
# a dependency-free page over the same JSON API).
_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:ui-monospace,Menlo,monospace;background:#111;color:#ddd;
      margin:0;padding:1rem}
 h1{font-size:1.1rem} h2{font-size:.95rem;margin:.8rem 0 .3rem;color:#8cf}
 table{border-collapse:collapse;width:100%;font-size:.8rem}
 td,th{border:1px solid #333;padding:.15rem .4rem;text-align:left}
 th{background:#1c1c1c;color:#aaa} tr:nth-child(even){background:#181818}
 .ok{color:#7c6} .bad{color:#e66} #status{color:#aaa;font-size:.8rem}
 pre{background:#181818;padding:.5rem;max-height:14rem;overflow:auto;
     font-size:.75rem}
 .spark{display:flex;align-items:center;gap:.5rem;font-size:.72rem}
 .spark svg{flex:none;background:#181818;border:1px solid #333}
 .sname{color:#aaa;overflow:hidden;text-overflow:ellipsis;
        white-space:nowrap;max-width:34rem}
 .sval{color:#7c6;margin-left:auto}
 #metrics{display:grid;grid-template-columns:repeat(2,minmax(0,1fr));
          gap:.1rem .8rem}
</style></head><body>
<h1>ray_tpu dashboard <span id="status"></span></h1>
<h2>Cluster</h2><div id="cluster"></div>
<h2>Serve / KV arena</h2><div id="serve"></div>
<h2>Serve / speculative decode</h2><div id="spec"></div>
<h2>Serve / prefix cache &amp; affinity routing</h2><div id="prefix"></div>
<h2>Serve / request latency breakdown (TTFT = queue + arena-wait +
prefill; TPOT)</h2><div id="reqlat"></div>
<h2>Serve / replica pressure</h2><table id="pressure"></table>
<h2>Serve / replica lifecycle (drains, deaths, resumes)</h2>
<div id="lifecycle"></div>
<h2>Serve / disaggregated prefill&rarr;decode (KV handoffs)</h2>
<div id="disagg"></div>
<h2>Train / input pipeline (stall, prefetch occupancy, bytes/s)</h2>
<div id="ingest"></div>
<h2>Train / goodput &amp; stragglers (wall-clock attribution, per-rank
step skew)</h2><div id="goodput"></div>
<h2>Train / elasticity (restarts by cause, world size, recovery time)</h2>
<div id="elastic"></div>
<h2>Pool / chip leases &amp; handoffs (serve&harr;train arbitration)</h2>
<div id="pool"></div><table id="poolleases"></table>
<h2>RL / weight sync &amp; rollout (trainer&rarr;generator versions,
staleness, swaps)</h2><div id="rl"></div>
<h2>Head / control plane (KV by namespace, pubsub fan-out, WAL,
RPC saturation)</h2><div id="head"></div>
<h2>Cluster / flight recorder (causal control-plane events —
``ray-tpu why &lt;id&gt;`` walks a chain)</h2><table id="flight"></table>
<h2>Metrics (last 5 min)</h2><div id="metrics"></div>
<h2>XLA programs (compiles / retraces / achieved)</h2>
<table id="xla"></table>
<h2>Profiler captures</h2><table id="captures"></table>
<h2>Checkpoints (committed manifests)</h2><table id="ckpts"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Node agents</h2><table id="agents"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<h2>Logs</h2><pre id="logs"></pre>
<script>
const esc=s=>String(s).replace(/[&<>"']/g,c=>({"&":"&amp;","<":"&lt;",
  ">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmt=v=>esc(typeof v==="object"?JSON.stringify(v):v);
function table(el,rows,cols){
  if(!rows.length){el.innerHTML="<tr><td>(none)</td></tr>";return;}
  cols=cols||Object.keys(rows[0]);
  el.innerHTML="<tr>"+cols.map(c=>`<th>${esc(c)}</th>`).join("")+"</tr>"+
    rows.map(r=>"<tr>"+cols.map(c=>`<td>${fmt(r[c])}</td>`).join("")
    +"</tr>").join("");
}
async function j(p){const r=await fetch(p);return r.json();}
function spark(pts){
  // Inline-SVG sparkline over [ts, value] points from the head TSDB.
  if(!pts.length)return "<svg width=\\"120\\" height=\\"22\\"></svg>";
  const w=120,h=22;
  const xs=pts.map(p=>p[0]),ys=pts.map(p=>p[1]);
  const x0=Math.min(...xs),x1=Math.max(...xs);
  const y0=Math.min(...ys),y1=Math.max(...ys);
  const sx=t=>x1===x0?w/2:1+(t-x0)/(x1-x0)*(w-2);
  const sy=v=>y1===y0?h/2:h-1-(v-y0)/(y1-y0)*(h-2);
  const d=pts.map(p=>sx(p[0]).toFixed(1)+","+sy(p[1]).toFixed(1)).join(" ");
  return `<svg width="${w}" height="${h}"><polyline fill="none" `+
         `stroke="#8cf" stroke-width="1" points="${d}"/></svg>`;
}
function sparkRows(data,limit){
  // Shared sparkline row builder for the metrics + serve panels.
  return data.slice(0,limit).map(s=>{
    const last=s.points.length?s.points[s.points.length-1][1]:0;
    const lbl=Object.entries(s.labels).filter(([k])=>k!=="pid")
      .map(([k,v])=>`${k}=${v}`).join(",");
    const val=Math.abs(last)>=100?last.toFixed(0):last.toFixed(3);
    return `<div class="spark">${spark(s.points)}<span class="sname">`+
      `${esc(s.name)}${lbl?"{"+esc(lbl)+"}":""}</span>`+
      `<span class="sval">${esc(val)}</span></div>`;
  }).join("");
}
async function metricsPanel(){
  // 3s avg buckets: ~100 points per 120px sparkline; full 0.25s
  // resolution would ship ~10x the payload for identical pixels. The
  // limit matches the rendered row count so big clusters don't ship
  // thousands of series per refresh just to be sliced client-side.
  const data=await j("/api/v1/metrics/query?since=300&agg=avg&step=3&limit=80");
  document.getElementById("metrics").innerHTML=
    sparkRows(data,80)||"(no series)";
}
async function requestLatencyPanel(){
  // TTFT attribution sparklines: the ray_tpu_serve_request_* histogram
  // _sum/_count series per (deployment, tenant). Queue vs arena-wait vs
  // prefill drifting apart points at WHERE a latency regression lives
  // before anyone opens a trace.
  const data=await j("/api/v1/metrics/query?series=ray_tpu_serve_request_*"+
                     "&since=300&agg=avg&step=3&limit=40");
  document.getElementById("reqlat").innerHTML=
    sparkRows(data,40)||"(no request telemetry)";
  const p=await j("/api/v1/serve/pressure");
  const rows=[];
  for(const [dep,reps] of Object.entries(p.deployments||{}))
    for(const r of reps)
      rows.push({deployment:dep,replica:r.replica,
        ongoing:r.ongoing??"",queue:r.queue_depth??"",
        slots:(r.active_slots??"")+"/"+(r.num_slots??""),
        "kv free":(r.kv_blocks_free??"")+"/"+(r.kv_blocks_total??""),
        "prefill tok":r.inflight_prefill_tokens??"",
        state:r.unreachable?"unreachable":"ok"});
  table(document.getElementById("pressure"),rows,
    ["deployment","replica","ongoing","queue","slots","kv free",
     "prefill tok","state"]);
}
async function servePanel(){
  // Serving hot-loop vitals: slot occupancy, decode rate, and the paged
  // KV arena (blocks used/total + fragmentation) per engine — the
  // sparkline makes admission stalls from arena exhaustion visible at a
  // glance.
  const data=await j("/api/v1/metrics/query?series=ray_tpu_cb_*"+
                     "&since=300&agg=avg&step=3&limit=60");
  document.getElementById("serve").innerHTML=
    sparkRows(data,60)||"(no serve engines)";
}
async function specPanel(){
  // Speculative decode vitals per engine: the live draft depth k (the
  // controller ladders it from the windowed accept rate — k stepping to
  // 0 means drafts stopped paying), the accept-rate gauge itself, and
  // the drafted/accepted token counters whose slope ratio is the
  // long-run acceptance. Accept rate sagging while k stays high means
  // the workload outran the drafter.
  const data=await j("/api/v1/metrics/query?series=ray_tpu_cb_spec_*"+
                     "&since=300&agg=avg&step=3&limit=20");
  document.getElementById("spec").innerHTML=
    sparkRows(data,20)||"(no speculative decode)";
}
async function prefixPanel(){
  // Prefix-cache effectiveness + router affinity: hit vs miss prompt
  // tokens, cached/refcounted arena blocks, and the affinity/overflow
  // decision counters. Hit tokens flatlining while miss tokens climb
  // means the radix cache is being evicted (arena too small) or traffic
  // stopped sharing prefixes; overflow spiking means a hot prefix's
  // home replica is saturated.
  const pc=await j("/api/v1/metrics/query?series=ray_tpu_cb_prefix_*"+
                   "&since=300&agg=avg&step=3&limit=20");
  const blocks=await j("/api/v1/metrics/query?"+
                   "series=ray_tpu_cb_kv_blocks_*&since=300&agg=avg"+
                   "&step=3&limit=20");
  const aff=await j("/api/v1/metrics/query?"+
                   "series=ray_tpu_serve_router_affinity_total"+
                   "&since=300&agg=avg&step=3&limit=10");
  const rows=pc.concat(
    blocks.filter(s=>s.name.endsWith("cached")||s.name.endsWith("shared")),
    aff);
  document.getElementById("prefix").innerHTML=
    sparkRows(rows,40)||"(no prefix-cache telemetry)";
}
async function ingestPanel(){
  // Train input pipeline: input-stall seconds vs step seconds says
  // whether the data plane or the device is the bottleneck; prefetch
  // occupancy flatlining at 0 with stalls climbing means the producer
  // (host decode / object store) can't keep up; the ingest bytes
  // counter's slope is the training data-plane bytes/s. Queried by
  // family (not the bare ray_tpu_train_* prefix) so the goodput/
  // straggler and elasticity series stay in their own panels.
  const parts=await Promise.all([
    j("/api/v1/metrics/query?series=ray_tpu_train_input_stall_*"+
      "&since=300&agg=avg&step=3&limit=10"),
    j("/api/v1/metrics/query?series=ray_tpu_train_prefetch_*"+
      "&since=300&agg=avg&step=3&limit=10"),
    j("/api/v1/metrics/query?series=ray_tpu_train_ingest_bytes_total"+
      "&since=300&agg=avg&step=3&limit=10"),
    j("/api/v1/metrics/query?series=ray_tpu_train_step_seconds*"+
      "&since=300&agg=avg&step=3&limit=10"),
    j("/api/v1/metrics/query?series=ray_tpu_train_tokens_per_s"+
      "&since=300&agg=avg&step=3&limit=10"),
    j("/api/v1/metrics/query?series=ray_tpu_train_reports_total"+
      "&since=300&agg=last&step=3&limit=10")]);
  document.getElementById("ingest").innerHTML=
    sparkRows([].concat(...parts),30)||"(no training ingest telemetry)";
}
async function goodputPanel(){
  // Goodput ledger: one stacked bar of the current attempt's wall-clock
  // attribution (step green = productive; stalls/sync/ckpt/recovery are
  // the badput the ledger names), plus per-rank step-time sparklines —
  // one rank's line drifting above the others IS the straggler, and the
  // straggler flag gauge stepping to 1 is the detector agreeing.
  const GCOL={step:"#7c6",input_stall:"#e66",sync:"#8cf",
              ckpt_block:"#fc6",recovery:"#c6f"};
  const frac=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_train_goodput_fraction&since=300&agg=last&step=3"+
    "&limit=12");
  let bar="",legend="";
  for(const s of frac){
    const c=s.labels.component||"?";
    const v=s.points.length?s.points[s.points.length-1][1]:0;
    if(v<=0)continue;
    bar+=`<div style="display:inline-block;height:14px;`+
      `width:${(v*100).toFixed(2)}%;background:${GCOL[c]||"#555"}" `+
      `title="${esc(c)} ${(v*100).toFixed(1)}%"></div>`;
    legend+=`<span style="color:${GCOL[c]||"#555"}">&#9632;</span> `+
      `${esc(c)} ${(v*100).toFixed(1)}% &nbsp;`;
  }
  const rank=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_train_rank_step_seconds*&since=300&agg=avg&step=3"+
    "&limit=20");
  const strag=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_train_straggler&since=300&agg=last&step=3&limit=10");
  document.getElementById("goodput").innerHTML=
    (bar?`<div style="border:1px solid #333;line-height:0">${bar}</div>`+
         `<div style="font-size:.72rem;margin:.15rem 0">${legend}</div>`
        :"")+
    (sparkRows(rank.concat(strag),30)||
     (bar?"":"(no train goodput telemetry)"));
}
async function elasticPanel(){
  // Elastic-trainer vitals: restarts_total{cause} stepping up says WHAT
  // keeps ending attempts (worker_lost vs hang vs preemption vs
  // resize); world_size moving shows shrink/grow-back re-formations;
  // recovery_seconds (histogram _sum/_count) is the failure-detection →
  // first-report-after-restart wall time.
  const restarts=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_train_restarts_total&since=300&agg=last&step=3&limit=20");
  const world=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_train_world_size&since=300&agg=last&step=3&limit=10");
  const rec=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_train_recovery_*&since=300&agg=avg&step=3&limit=10");
  document.getElementById("elastic").innerHTML=
    sparkRows(restarts.concat(world,rec),40)||"(no elastic trainers)";
}
async function poolPanel(){
  // Chip-pool arbitration: chips per ledger owner (serve/train/
  // in_flight always sum to the pool total — watch conservation at a
  // glance), handoff counters, SLO reversals, plus the live lease table
  // with state-machine stage and deadline. Autoscaler health (tick
  // failures, allocation backoff) rides along: both planes share L7.
  const series=await j("/api/v1/metrics/query?series=ray_tpu_pool_*"+
                       "&since=300&agg=last&step=3&limit=30");
  const aseries=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_autoscaler_*&since=300&agg=last&step=3&limit=10");
  const p=await j("/api/v1/pool");
  let head="";
  if(p.allocation){
    const a=p.allocation;
    head=`<div style="font-size:.8rem;margin:.15rem 0">serve=${a.serve} `+
      `train=${a.train} in_flight=${a.in_flight} / total=${a.total}`+
      (p.last_reversal?` &nbsp; last SLO ${esc(p.last_reversal.action)}: `+
        `${esc(p.last_reversal.signal)} on ${esc(p.last_reversal.lease_id)}`
        :"")+
      (p.autoscaler&&p.autoscaler.last_tick_error?
        ` &nbsp; <span class="bad">autoscaler: `+
        `${esc(p.autoscaler.last_tick_error)}</span>`:"")+`</div>`;
  }
  document.getElementById("pool").innerHTML=
    head+(sparkRows(series.concat(aseries),40)||
          (head?"":"(no chip-pool arbiter)"));
  table(document.getElementById("poolleases"),
    (p.leases||[]).slice(0,20).map(l=>({
      lease:l.lease_id,direction:l.donor+"→"+l.recipient,
      chips:l.chips,stage:l.stage,
      deadline:l.deadline_ts?
        new Date(l.deadline_ts*1000).toLocaleTimeString():"",
      since:l.history&&l.history.length?
        new Date(l.history[l.history.length-1][1]*1000)
          .toLocaleTimeString():""})),
    ["lease","direction","chips","stage","deadline","since"]);
}
async function rlPanel(){
  // RL post-training loop: the trainer/generator version gauges moving
  // in lockstep say the sync plane is live (a widening gap IS the sync
  // lag); sync seconds/bytes split by path (publish vs subscribe vs
  // checkpoint fallback); rollout staleness is how off-policy the
  // experience stream is; swaps_total{cause} says how each generator
  // got its weights; shed_total{subscriber} names a lagging replica.
  const series=await j("/api/v1/metrics/query?series=ray_tpu_rl_*"+
                       "&since=300&agg=last&step=3&limit=30");
  document.getElementById("rl").innerHTML=
    sparkRows(series,40)||"(no RL weight-sync activity)";
}
async function headPanel(){
  // Head load plane: where the single control-plane process's capacity
  // goes. KV ops/bytes by namespace name the chatty subsystem, pubsub
  // fan-out latency + drops name the slow subscriber, WAL watermark lag
  // says whether durability keeps up, and the rpc queue-wait/occupancy
  // series are the saturation signal bench_control.py sweeps to a knee.
  const gcs=await j("/api/v1/metrics/query?series=ray_tpu_gcs_*"+
                    "&since=300&agg=avg&step=3&limit=40");
  const rpc=await j("/api/v1/metrics/query?series=ray_tpu_rpc_*"+
                    "&since=300&agg=avg&step=3&limit=20");
  document.getElementById("head").innerHTML=
    sparkRows(gcs.concat(rpc),60)||"(no head samples yet)";
}
async function flightPanel(){
  // Flight recorder: newest control-plane events (lease transitions,
  // drains, preemption notices, recoveries, chaos injections). The
  // cause column chains each event to the one that triggered it —
  // `ray-tpu why request|run|lease|node <id>` walks the whole chain.
  const evs=await j("/api/v1/events?since=600&limit=200");
  table(document.getElementById("flight"),
    evs.slice(-25).reverse().map(e=>({
      at:new Date(e.ts*1000).toLocaleTimeString(),
      event:e.event_id,type:e.type,
      subject:Object.entries(e.subject||{})
        .map(([k,v])=>`${k}=${v}`).join(","),
      cause:e.cause||"",
      detail:Object.entries(e.attrs||{}).slice(0,4)
        .map(([k,v])=>`${k}=${v}`).join(",")})),
    ["at","event","type","subject","cause","detail"]);
}
async function lifecyclePanel(){
  // Serve failure plane: drains_total{cause} stepping up says WHY
  // replicas leave rotation (scale_down vs preemption), deaths_total
  // splits probe-found deaths from died-while-draining, resumes_total
  // {cause} is the in-flight recovery rate (resubmit = nothing lost,
  // resume = mid-decode replay, drain_reject = free re-route), and the
  // drain histogram (_sum/_count) is time-to-quiesce by outcome.
  const reps=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_serve_replica_*&since=300&agg=last&step=3&limit=30");
  const drain=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_serve_drain_seconds*&since=300&agg=avg&step=3"+
    "&limit=10");
  document.getElementById("lifecycle").innerHTML=
    sparkRows(reps.concat(drain),40)||"(no replica lifecycle events)";
}
async function disaggPanel(){
  // Disaggregated serving: handoff_total{outcome} is the exactly-once
  // ledger (ok vs prefill_died/decode_died recoveries vs crc_mismatch
  // — any nonzero mismatch is an escalation), kv_transfer_bytes/blocks
  // {direction} are the export→channel→import volume (the legs should
  // track each other; a gap means orphaned channels), and the transfer
  // seconds histogram is the handoff's latency contribution to TTFT.
  const hand=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_serve_handoff_total&since=300&agg=last&step=3"+
    "&limit=20");
  const xfer=await j("/api/v1/metrics/query?"+
    "series=ray_tpu_serve_kv_transfer_*&since=300&agg=avg&step=3"+
    "&limit=30");
  document.getElementById("disagg").innerHTML=
    sparkRows(hand.concat(xfer),40)||"(no KV handoffs yet)";
}
async function xlaPanel(){
  // Compile/retrace table per (node, program) from the xla series the
  // push plane lands in the TSDB, plus the registered profiler captures.
  const data=await j("/api/v1/metrics/query?series=ray_tpu_xla_*"+
                     "&agg=last&step=10&since=600&limit=400");
  const rows={};
  for(const s of data){
    // One row per (process, program): XLA series carry pid labels, and
    // merging pids would show one arbitrary process's counters.
    const node=(s.labels.node_id||s.labels.role||"?")+
      (s.labels.pid?" pid="+s.labels.pid:"");
    const key=node+"|"+(s.labels.program||"");
    const last=s.points.length?s.points[s.points.length-1][1]:0;
    (rows[key]=rows[key]||{node,program:s.labels.program||""})[s.name]=last;
  }
  const fmt1=v=>v==null?"":(v>=1e9?(v/1e9).toFixed(2)+"G":
    v>=1e6?(v/1e6).toFixed(2)+"M":(+v).toFixed(v>=100?0:2));
  table(document.getElementById("xla"),
    Object.values(rows).map(r=>({
      node:r.node,program:r.program,
      compiles:fmt1(r["ray_tpu_xla_compiles_total"]),
      retraces:fmt1(r["ray_tpu_xla_retraces_total"]),
      "flops/s":fmt1(r["ray_tpu_xla_achieved_flops_per_s"]),
      "bytes/s":fmt1(r["ray_tpu_xla_achieved_bandwidth_bytes_per_s"]),
      mfu:fmt1(r["ray_tpu_xla_model_flops_utilization"])})),
    ["node","program","compiles","retraces","flops/s","bytes/s","mfu"]);
  table(document.getElementById("captures"),
    (await j("/api/v1/profile/list")).slice(0,20).map(e=>({
      capture:e.capture_id,status:e.status,node:e.node_id,pid:e.pid,
      trace_dir:e.trace_dir||"",files:e.files||""})));
  table(document.getElementById("ckpts"),
    (await j("/api/v1/checkpoints")).slice(0,20).map(m=>({
      run:m.run,step:m.step,nprocs:m.nprocs,bytes:m.bytes,
      dir:m.dir||"",
      at:new Date((m.ts||0)*1000).toLocaleTimeString()})));
}
async function refresh(){
  try{
    const cs=await j("/api/cluster_status");
    document.getElementById("cluster").innerHTML=
      `<span class="ok">${cs.nodes_alive}/${cs.nodes_total} nodes</span>`+
      ` &nbsp; total=${fmt(cs.resources_total)}`+
      ` avail=${fmt(cs.resources_available)}`;
    table(document.getElementById("nodes"),await j("/api/nodes"));
    table(document.getElementById("agents"),await j("/api/agents"));
    table(document.getElementById("actors"),await j("/api/actors"));
    table(document.getElementById("jobs"),await j("/api/jobs"));
    table(document.getElementById("tasks"),
          (await j("/api/tasks")).slice(-30).reverse());
    const logs=await j("/api/logs");
    document.getElementById("logs").textContent=logs.slice(-200)
      .map(l=>`[${l.worker} ${l.pid}] ${l.line}`).join("\\n");
    await metricsPanel();
    await servePanel();
    await specPanel();
    await prefixPanel();
    await requestLatencyPanel();
    await lifecyclePanel();
    await disaggPanel();
    await ingestPanel();
    await goodputPanel();
    await elasticPanel();
    await poolPanel();
    await rlPanel();
    await headPanel();
    await flightPanel();
    await xlaPanel();
    document.getElementById("status").textContent=
      "updated "+new Date().toLocaleTimeString();
  }catch(e){
    document.getElementById("status").textContent="refresh failed: "+e;
  }
}
refresh();setInterval(refresh,2000);
</script></body></html>
"""


class Dashboard:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 8265):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ray_tpu._private import rpc
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        gcs = rpc.get_stub("GcsService", gcs_address)

        def nodes():
            return [{
                "node_id": n.node_id, "address": n.address, "alive": n.alive,
                "resources": dict(n.resources), "available": dict(n.available),
                "labels": dict(n.labels),
            } for n in gcs.GetNodes(pb.GetNodesRequest()).nodes]

        def actors():
            return [{
                "actor_id": a.actor_id.hex(), "class_name": a.class_name,
                "state": a.state, "name": a.name, "node_id": a.node_id,
                "num_restarts": a.num_restarts,
            } for a in gcs.ListActors(
                pb.ListActorsRequest(all_namespaces=True)).actors]

        def jobs():
            keys = gcs.KvKeys(pb.KvRequest(ns="job", prefix="")).keys
            out = []
            for k in keys:
                r = gcs.KvGet(pb.KvRequest(ns="job", key=k))
                if r.found:
                    out.append(json.loads(r.value))
            return out

        log_buffer: deque = deque(maxlen=2000)

        def _log_subscriber():
            # The dashboard tails worker logs off the LOG pubsub channel
            # into a ring buffer for /api/logs (reference: dashboard log
            # viewing over the log agents).
            while True:
                try:
                    stream = gcs.Subscribe(pb.SubscribeRequest(
                        channels=["LOG"], subscriber_id="dashboard"))
                    for msg in stream:
                        try:
                            rec = pickle.loads(msg.data)
                            for line in rec.get("lines", ()):
                                log_buffer.append({
                                    "worker": rec.get("name", "?"),
                                    "pid": rec.get("pid"),
                                    "stream": rec.get("stream"),
                                    "line": line})
                        except Exception:  # noqa: BLE001
                            pass
                except Exception:  # noqa: BLE001
                    pass
                # Streams can also end CLEANLY (GCS stopping/restarting);
                # always back off before re-subscribing.
                time.sleep(1.0)

        threading.Thread(target=_log_subscriber, daemon=True).start()

        def logs():
            return list(log_buffer)

        def tasks():
            reply = gcs.KvGet(pb.KvRequest(ns="__task_events__",
                                           key="recent"))
            return pickle.loads(reply.value) if reply.found else []

        # Per-path cached fan-out over the node agents (reference:
        # dashboard agents): resolve addresses from the __agents__ KV
        # registry, probe CONCURRENTLY (dead agents cost one shared 2s
        # timeout, not 2s each), and cache briefly so the frontend's poll
        # loop can't pile requests behind unreachable agents. Shared by
        # /api/agents (stats) and /metrics (Prometheus rollup).
        probe_cache: dict = {}
        probe_lock = threading.Lock()

        def probe_agents(path, transform, ttl_s=2.0):
            import urllib.request
            from concurrent.futures import ThreadPoolExecutor

            with probe_lock:
                cached = probe_cache.get(path)
                if cached and time.monotonic() - cached[0] < ttl_s:
                    return cached[1]

            def probe(node_id):
                r = gcs.KvGet(pb.KvRequest(ns="__agents__", key=node_id))
                if not r.found:
                    return None
                addr = r.value.decode()
                try:
                    with urllib.request.urlopen(
                            f"http://{addr}{path}", timeout=2) as resp:
                        return transform(node_id, addr, resp.read(), None)
                except Exception as e:  # noqa: BLE001
                    return transform(node_id, addr, None, e)

            keys = list(gcs.KvKeys(pb.KvRequest(ns="__agents__",
                                                prefix="")).keys)
            out = []
            if keys:
                with ThreadPoolExecutor(max_workers=min(16,
                                                        len(keys))) as ex:
                    out = [e for e in ex.map(probe, keys)
                           if e is not None]
            with probe_lock:
                probe_cache[path] = (time.monotonic(), out)
            return out

        def agents():
            def transform(node_id, addr, body, err):
                entry = {"node_id": node_id, "agent_address": addr}
                if err is not None:
                    entry["error"] = str(err)
                else:
                    entry["stats"] = json.loads(body)
                return entry

            return probe_agents("/stats", transform)

        def cluster_metrics() -> str:
            """Cluster-wide Prometheus rollup (reference: per-node metrics
            agents scraped into one Prometheus view): head-process series
            plus every node agent's /metrics, each series labeled with its
            FULL node_id (truncation could collide nodes into duplicate
            samples, which Prometheus rejects). TYPE/HELP metadata is
            deduplicated across parts for the same reason."""
            from ray_tpu.util.metrics import prometheus_text

            def transform(node_id, addr, body, err):
                if err is not None:
                    return ""
                return _label_series(body.decode(), "node_id", node_id)

            parts = [_label_series(prometheus_text(), "node_id", "head")]
            parts.extend(probe_agents("/metrics", transform))
            return _merge_expositions(parts)

        def metrics_series():
            reply = gcs.KvGet(pb.KvRequest(ns="__metrics__", key="series"))
            return pickle.loads(reply.value) if reply.found else []

        # XLA profiling plane (reference: the dashboard drives on-demand
        # profiler runs through the per-node agents; here the command is
        # a GCS pubsub publish and the results register in the KV).
        def profile_list():
            from ray_tpu._private import xla_monitor

            return xla_monitor.list_captures(gcs_address)

        def profile_capture(params):
            from ray_tpu._private import xla_monitor

            capture_id = xla_monitor.request_capture(
                gcs_address, node=params.get("node", "*"),
                duration_s=float(params.get("duration", 2.0)))
            return {"capture_id": capture_id}

        def xla_programs():
            from ray_tpu._private import xla_monitor

            return xla_monitor.list_programs(gcs_address)

        def checkpoints():
            from ray_tpu.checkpoint.plane import list_manifests_kv

            return list_manifests_kv(gcs)

        def pool_state():
            """Chip-pool ledger + autoscaler health straight from the
            GCS KV (the arbiter journals every lease transition into
            ``__pool__``; the reconciler mirrors its summary into
            ``autoscaler/status``) — renderable with no runtime."""
            from ray_tpu.autoscaler.arbiter import read_pool_state

            out = read_pool_state(gcs_address)
            reply = gcs.KvGet(pb.KvRequest(ns="autoscaler",
                                           key="status"))
            out["autoscaler"] = (json.loads(reply.value)
                                 if reply.found else None)
            return out

        def serve_pressure():
            """Per-replica serve pressure (queue depth, KV blocks free,
            in-flight prefill tokens) mirrored into the GCS KV by the
            serve controller's reconcile loop — the future
            prefix-affinity/KV-pressure router reads the same signal."""
            reply = gcs.KvGet(pb.KvRequest(ns="__serve__",
                                           key="pressure"))
            if not reply.found:
                return {"ts": 0, "deployments": {}}
            return json.loads(reply.value)

        def metrics_query(params):
            """Translate HTTP query params into a TSDB query served by the
            GCS ``__metrics__`` KV namespace: ``series`` (exact name, or
            prefix with trailing ``*``), ``since``/``until`` (seconds ago,
            or absolute unix ts), ``label.<k>=<v>`` filters, ``agg``
            (avg/min/max/sum/last) with ``step`` seconds."""
            q = {
                "name": params.get("series") or None,
                "since": float(params.get("since", 300.0)),
                "until": (float(params["until"])
                          if "until" in params else None),
                "labels": {k[len("label."):]: v for k, v in params.items()
                           if k.startswith("label.")},
                "agg": params.get("agg") or None,
                "step": float(params["step"]) if "step" in params else None,
                "limit": (int(params["limit"])
                          if "limit" in params else None),
            }
            reply = gcs.KvGet(pb.KvRequest(ns="__metrics__",
                                           key=json.dumps(q)))
            if not reply.found:
                raise ValueError(
                    f"bad metrics query: {reply.value.decode()}")
            return pickle.loads(reply.value)

        def flight_events(params):
            """Flight-recorder query: ``type`` (comma-separated event
            types), ``subject.<k>=<v>`` filters, ``since``/``until``
            (seconds ago, or absolute unix ts), ``limit`` — answered
            server-side by the GCS-journaled event store through the
            reserved ``__events__`` KV namespace (same transport idiom
            as the ``__metrics__`` TSDB queries)."""
            types = [t for t in (params.get("type") or "").split(",")
                     if t]
            q = {
                "types": types or None,
                "subject": {k[len("subject."):]: v
                            for k, v in params.items()
                            if k.startswith("subject.")},
                "since": float(params.get("since", 600.0)),
                "until": (float(params["until"])
                          if "until" in params else None),
                "limit": int(params.get("limit", 1000)),
            }
            reply = gcs.KvGet(pb.KvRequest(ns="__events__",
                                           key=json.dumps(q)))
            if not reply.found:
                raise ValueError(
                    f"bad flight-event query: {reply.value.decode()}")
            return pickle.loads(reply.value)

        def cluster_status():
            ns = nodes()
            total, avail = {}, {}
            for n in ns:
                if not n["alive"]:
                    continue
                for k, v in n["resources"].items():
                    total[k] = total.get(k, 0) + v
                for k, v in n["available"].items():
                    avail[k] = avail.get(k, 0) + v
            return {"nodes_alive": sum(n["alive"] for n in ns),
                    "nodes_total": len(ns),
                    "resources_total": total, "resources_available": avail}

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                from urllib.parse import parse_qs, urlsplit

                parts = urlsplit(self.path)
                path = parts.path
                params = {k: v[0] for k, v
                          in parse_qs(parts.query).items()}
                try:
                    if path == "/metrics":
                        body = cluster_metrics().encode()
                        ctype = "text/plain; version=0.0.4"
                    elif path in ("/", "/index.html"):
                        body = _INDEX_HTML.encode()
                        ctype = "text/html; charset=utf-8"
                    elif path == "/api/v1/metrics/series":
                        body = json.dumps(metrics_series()).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/metrics/query":
                        body = json.dumps(metrics_query(params)).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/profile/list":
                        body = json.dumps(profile_list()).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/profile/capture":
                        body = json.dumps(profile_capture(params)).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/xla/programs":
                        body = json.dumps(xla_programs()).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/checkpoints":
                        body = json.dumps(checkpoints()).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/serve/pressure":
                        body = json.dumps(serve_pressure()).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/pool":
                        body = json.dumps(pool_state()).encode()
                        ctype = "application/json"
                    elif path == "/api/v1/events":
                        body = json.dumps(flight_events(params),
                                          default=str).encode()
                        ctype = "application/json"
                    else:
                        route = {
                            "/api/cluster_status": cluster_status,
                            "/api/nodes": nodes,
                            "/api/actors": actors,
                            "/api/jobs": jobs,
                            "/api/logs": logs,
                            "/api/tasks": tasks,
                            "/api/agents": agents,
                        }.get(path)
                        if route is None:
                            self.send_response(404)
                            self.end_headers()
                            return
                        body = json.dumps(route()).encode()
                        ctype = "application/json"
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    body = json.dumps({"error": str(e)}).encode()
                    ctype = "application/json"
                    self.send_response(500)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def stop(self):
        self.server.shutdown()
