"""ray_tpu CLI (reference: ``python/ray/scripts/scripts.py`` — ray
start/stop/status/submit/...). Run as ``python -m ray_tpu.scripts.cli``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

# NOT /tmp/ray_tpu: a directory named like the package next to a
# script's cwd becomes an importable namespace package and shadows
# the real ray_tpu.
STATE_DIR = "/tmp/ray_tpu_state"
ADDRESS_FILE = os.path.join(STATE_DIR, "address")
PIDS_FILE = os.path.join(STATE_DIR, "pids")


def _save_pid(pid: int):
    os.makedirs(STATE_DIR, exist_ok=True)
    with open(PIDS_FILE, "a") as f:
        f.write(f"{pid}\n")


def _read_port(proc, tag: str, timeout_s: float = 30.0) -> int:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline().decode().strip()
        if line.startswith(f"{tag}="):
            return int(line.split("=", 1)[1])
        if not line and proc.poll() is not None:
            break
    raise RuntimeError(f"failed to read {tag} from subprocess")


def cmd_start(args):
    os.makedirs(STATE_DIR, exist_ok=True)
    env = dict(os.environ)
    if args.head:
        gcs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs.server",
             "--port", str(args.port or 0)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        _save_pid(gcs.pid)
        gcs_port = _read_port(gcs, "GCS_PORT")
        address = f"127.0.0.1:{gcs_port}"
        with open(ADDRESS_FILE, "w") as f:
            f.write(address)
        print(f"GCS started at {address}")
    else:
        address = args.address or _auto_address()

    nm_cmd = [sys.executable, "-m", "ray_tpu._private.node_manager.server",
              "--gcs-address", address,
              "--num-cpus", str(args.num_cpus or os.cpu_count()),
              # None = auto-detect on the node; an explicit 0 opts out.
              "--num-tpus", str(-1 if args.num_tpus is None
                                else args.num_tpus)]
    if args.resources:
        nm_cmd += ["--resources", args.resources]
    if args.labels:
        nm_cmd += ["--labels", args.labels]
    nm = subprocess.Popen(nm_cmd, stdout=subprocess.PIPE,
                          stderr=subprocess.DEVNULL, env=env)
    _save_pid(nm.pid)
    nm_port = _read_port(nm, "NODE_PORT")
    print(f"Node manager started at 127.0.0.1:{nm_port}")

    if args.head and args.ray_client_server_port >= 0:
        # ray:// driver proxy (reference: Ray Client server on 10001).
        proxy = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.client_proxy",
             "--address", address, "--host", "0.0.0.0",
             "--port", str(args.ray_client_server_port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        _save_pid(proxy.pid)
        proxy_port = _read_port(proxy, "CLIENT_PROXY_PORT")
        print(f"ray:// driver proxy on port {proxy_port} "
              f"(connect: ray_tpu.init(address='ray://<host>:"
              f"{proxy_port}'))")

    if args.head and args.dashboard:
        from ray_tpu.dashboard import Dashboard

        dash = Dashboard(address, port=args.dashboard_port)
        print(f"Dashboard at http://127.0.0.1:{dash.port}")
        print(f"\nConnect with: ray_tpu.init(address={address!r})")
        print("Press Ctrl-C to keep running in foreground, or re-run with "
              "--block to stay attached.")
        if args.block:
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                pass
    elif args.head:
        print(f"\nConnect with: ray_tpu.init(address={address!r})")


def _auto_address() -> str:
    if os.environ.get("RAY_TPU_ADDRESS"):
        return os.environ["RAY_TPU_ADDRESS"]
    if os.path.exists(ADDRESS_FILE):
        with open(ADDRESS_FILE) as f:
            return f.read().strip()
    raise SystemExit("no cluster address: pass --address or start a head")


def cmd_stop(args):
    if not os.path.exists(PIDS_FILE):
        print("nothing to stop")
        return
    with open(PIDS_FILE) as f:
        pids = [int(line) for line in f if line.strip()]
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"stopped {pid}")
        except OSError:
            pass
    os.remove(PIDS_FILE)
    if os.path.exists(ADDRESS_FILE):
        os.remove(ADDRESS_FILE)


def cmd_status(args):
    address = args.address or _auto_address()
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    gcs = rpc.get_stub("GcsService", address)
    nodes = gcs.GetNodes(pb.GetNodesRequest()).nodes
    print(f"Cluster at {address}: "
          f"{sum(n.alive for n in nodes)}/{len(nodes)} nodes alive")
    for n in nodes:
        state = "ALIVE" if n.alive else "DEAD"
        print(f"  {n.node_id[:12]} {state:6} {n.address:22} "
              f"resources={dict(n.resources)}")
    actors = gcs.ListActors(pb.ListActorsRequest(all_namespaces=True)).actors
    if actors:
        print(f"Actors ({len(actors)}):")
        for a in actors:
            print(f"  {a.actor_id.hex()[:12]} {a.state:10} {a.class_name}")


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    address = args.address or _auto_address()
    client = JobSubmissionClient(address)
    job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
    print(f"submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout_s=args.timeout)
        print(f"{job_id}: {status}")
        print(client.get_job_logs(job_id))
        if status != "SUCCEEDED":
            raise SystemExit(1)


def cmd_jobs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address or _auto_address())
    for info in client.list_jobs():
        if info:
            print(f"{info['job_id']:32} {info['status']:10} "
                  f"{info['entrypoint']}")


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=args.address or _auto_address(),
                 ignore_reinit_error=True)
    return ray_tpu


def cmd_timeline(args):
    """Chrome-trace dump of cluster task events (reference: ``ray
    timeline`` -> GlobalState.chrome_tracing_dump, _private/state.py:442),
    merged with the flight recorder's control-plane events (rendered as
    zero-duration slices whose cause links become flow arrows). Open the
    output in chrome://tracing or https://ui.perfetto.dev."""
    _connect(args)
    from ray_tpu._private import events as _events
    from ray_tpu.util import state
    from ray_tpu.util.tracing import spans_to_chrome_events

    events = state.task_timeline()
    flight = state.list_flight_events(limit=100000)
    if flight:
        events = events + spans_to_chrome_events(
            _events.flight_span_records(flight))
    out = args.output or f"ray-tpu-timeline-{int(time.time())}.json"
    with open(out, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} trace events to {out} "
          f"({len(flight)} flight-recorder events)")


def _no_records_exit(what: str, want, tracing_gated: bool = True):
    """The one empty-result message for every record-lookup command
    (``trace request``, ``trace train``, ``why``): same diagnosis —
    flushing is periodic and drops are accounted — phrased once. The
    flight recorder is always on, so ``why`` skips the tracing hint."""
    gate = ("was the cluster started with RAY_TPU_TRACING=1, and has "
            if tracing_gated else "has ")
    raise SystemExit(
        f"no {what} found for {want!r} — {gate}the buffer flushed "
        f"(reporters flush every 0.2s)? Drops are counted in "
        f"ray_tpu_events_dropped_total.")


def cmd_trace(args):
    """Path tracing: reconstruct ONE serve request's — or ONE training
    run's — life as a chrome-trace timeline.

    ``ray-tpu trace request <id>``: the id can be the request id
    (``x-request-id`` header, or minted at ingress and carried on every
    span of the request) or a trace id. Matching finds the request's
    trace, then pulls EVERY span sharing its trace id — ingress, route,
    replica dispatch, engine queue / arena-wait / prefill, and
    per-sync-window decode spans.

    ``ray-tpu trace train <run>``: the id is the run name
    (``RunConfig.name``) or a trace id; the trace spans the whole run —
    ``train.run`` → per-attempt ``train.attempt`` → scored
    ``train.step_window`` spans, plus a ``train.recovery`` tree
    (teardown / backoff / reacquire / restore_first_step) per elastic
    recovery. Multiple runs may share a name; the newest is shown.

    Both print an offset-ordered summary plus a chrome://tracing /
    perfetto JSON file. Spans exist only when the cluster ran with
    RAY_TPU_TRACING=1."""
    _connect(args)
    from ray_tpu.util import state
    from ray_tpu.util.tracing import spans_to_chrome_events

    spans = [e for e in state.list_tasks(limit=100000, include_spans=True)
             if e.get("state") == "SPAN"]
    want = args.id
    if args.kind == "train":
        matched = [e for e in spans
                   if e["name"].startswith("train.")
                   and want in (e.get("run"), e.get("trace_id"))]
        if not matched:
            _no_records_exit("train spans", want)
        by_trace = {}
        for e in matched:
            by_trace.setdefault(e["trace_id"], []).append(e["ts"])
        # Several runs can share a name (restarted experiments): show
        # the newest and say so.
        trace_id = max(by_trace, key=lambda t: max(by_trace[t]))
        if len(by_trace) > 1:
            print(f"note: {len(by_trace)} runs named {want!r} have "
                  f"spans; showing the newest (trace {trace_id}) — "
                  f"pass a trace id to pick another")
    else:
        trace_ids = {e["trace_id"] for e in spans
                     if want in (e.get("request_id"), e.get("trace_id"))}
        if not trace_ids:
            _no_records_exit("spans", want)
        if len(trace_ids) > 1:
            raise SystemExit(
                f"id {want!r} matches {len(trace_ids)} traces — pass the "
                f"full request id from the x-request-id header")
        trace_id = trace_ids.pop()
    mine = sorted((e for e in spans if e["trace_id"] == trace_id),
                  key=lambda e: e["ts"])
    out = args.output or f"ray-tpu-trace-{want[:16]}.json"
    with open(out, "w") as f:
        json.dump(spans_to_chrome_events(mine), f)
    t0 = mine[0]["ts"]
    print(f"trace {trace_id} ({len(mine)} spans):")
    for e in mine:
        off_ms = (e["ts"] - t0) * 1e3
        dur_ms = e.get("dur", 0.0) * 1e3
        extra = ""
        if e.get("tokens") is not None:
            extra = f"  tokens={e['tokens']}"
        for k in ("attempt", "world", "window", "cause", "outcome",
                  "max_skew", "stragglers"):
            if e.get(k) not in (None, ""):
                extra += f"  {k}={e[k]}"
        print(f"  +{off_ms:9.2f}ms {dur_ms:9.2f}ms  {e['name']:24} "
              f"[{e.get('kind', '')}] worker={e.get('worker_id', '')}"
              f"{extra}")
    print(f"wrote chrome trace to {out} "
          f"(open in chrome://tracing or https://ui.perfetto.dev)")


# flight-recorder `why` kinds → the subject key each one pins.
_WHY_SUBJECT_KEY = {"request": "request_id", "run": "run",
                    "lease": "lease_id", "node": "node"}


def cmd_why(args):
    """Causal narrative for ONE subject from the cluster flight
    recorder: ``ray-tpu why request|run|lease|node <id>``.

    Finds every control-plane event whose subject carries the id, walks
    cause links both ways (the events that triggered it and the events
    it triggered) plus a subject-join round (events sharing a lease /
    replica / node / run with the chain), then merges tracing spans
    that belong to the same request or run into one time-ordered story
    — e.g. chaos preempt injection → preemption notice → replica drain
    → journaled resume → lease reversal, each line carrying its event
    id and the id of its cause."""
    _connect(args)
    from ray_tpu._private import events as _events
    from ray_tpu.util import state

    key = _WHY_SUBJECT_KEY[args.kind]
    want = str(args.id)
    records = state.list_flight_events(limit=100000)
    seeds = [r["event_id"] for r in records
             if str((r.get("subject") or {}).get(key, "")) == want]
    if not seeds:
        _no_records_exit(f"flight events keyed {key}", want,
                         tracing_gated=False)
    chain = _events.causal_chain(records, seeds)
    by_id = {r["event_id"]: r for r in chain}
    # Tracing spans sharing an id with the chain tell the data-plane
    # half of the story (what the request/run was doing when the
    # control plane acted); spans are garnish — missing tracing or a
    # failed span query must never sink the narrative.
    spans = []
    try:
        subj_vals = {v for r in chain
                     for v in (r.get("subject") or {}).values()}
        spans = [e for e in state.list_tasks(limit=100000,
                                             include_spans=True)
                 if e.get("state") == "SPAN"
                 and (e.get("request_id") in subj_vals
                      or e.get("run") in subj_vals
                      or e.get("trace_id") in subj_vals)]
    except Exception:  # noqa: BLE001
        spans = []
    rows = ([("event", r["ts"], r) for r in chain]
            + [("span", s["ts"], s) for s in spans])
    rows.sort(key=lambda t: t[1])
    t0 = rows[0][1]
    print(f"why {args.kind} {want}: {len(chain)} events"
          + (f", {len(spans)} spans" if spans else ""))
    for what, ts, r in rows:
        off_ms = (ts - t0) * 1e3
        if what == "span":
            print(f"  +{off_ms:9.2f}ms  {'(span)':16}  "
                  f"{r['name']:22} dur={r.get('dur', 0.0) * 1e3:.2f}ms "
                  f"worker={r.get('worker_id', '')}")
            continue
        subject = ",".join(f"{k}={v}" for k, v in
                           sorted((r.get("subject") or {}).items()))
        attrs = ",".join(
            f"{k}={v}" for k, v in sorted((r.get("attrs") or {}).items())
            if v not in (None, ""))
        cause = r.get("cause") or ""
        arrow = ""
        if cause:
            arrow = ("  <= " + cause
                     + ("" if cause in by_id else " (outside chain)"))
        print(f"  +{off_ms:9.2f}ms  {r['event_id']}  {r['type']:22} "
              f"[{subject}]" + (f" {attrs}" if attrs else "") + arrow)
    if args.output:
        with open(args.output, "w") as f:
            json.dump({"events": chain, "spans": spans}, f,
                      indent=2, default=str)
        print(f"wrote chain to {args.output}")


def cmd_list(args):
    """State CLI (reference: ``ray list tasks|actors|...``,
    ``ray/util/state/state_cli.py``)."""
    _connect(args)
    from ray_tpu.util import state

    kind = args.kind
    if kind == "nodes":
        rows = state.list_nodes()
    elif kind == "actors":
        rows = state.list_actors()
    elif kind == "tasks":
        rows = state.list_tasks(limit=args.limit)
    elif kind == "objects":
        rows = state.memory_summary()["objects"][:args.limit]
    elif kind == "placement-groups":
        rows = state.list_placement_groups()
    elif kind == "cluster-events":
        rows = state.list_cluster_events(limit=args.limit)
    else:  # pragma: no cover - argparse choices guard this
        raise SystemExit(f"unknown kind {kind}")
    if args.format == "json":
        print(json.dumps(rows, indent=2, default=str))
        return
    for r in rows[:args.limit]:
        print("  ".join(f"{k}={v}" for k, v in r.items()))
    print(f"({min(len(rows), args.limit)} of {len(rows)} rows)")


def cmd_memory(args):
    """Cluster object-store report (reference: ``ray memory``)."""
    _connect(args)
    from ray_tpu.util import state

    rep = state.memory_summary()
    print(f"Tracked objects: {rep['num_tracked']}  "
          f"total bytes: {rep['total_bytes']}  "
          f"freed (remembered): {rep['num_freed_remembered']}")
    rows = sorted(rep["objects"], key=lambda o: -o["size"])
    for o in rows[:args.limit]:
        holders = ", ".join(f"{h[:12]}:{c}" for h, c in o["holders"].items())
        locs = ", ".join(n[:8] for n in o["locations"]) or "inline/owner"
        print(f"  {o['object_id'][:16]} {o['size']:>12}B  "
              f"nodes=[{locs}]  refs=[{holders}]")


def _metrics_kv(address, key: str):
    """Read the GCS-hosted TSDB through the reserved __metrics__ KV
    namespace (key "series" lists; a JSON dict key queries)."""
    import pickle

    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    gcs = rpc.get_stub("GcsService", address)
    reply = gcs.KvGet(pb.KvRequest(ns="__metrics__", key=key))
    if not reply.found:
        raise SystemExit(f"metrics query failed: {reply.value.decode()}")
    return pickle.loads(reply.value)


def _metrics_query_key(args, since: float = None) -> str:
    labels = dict(kv.split("=", 1) for kv in (args.label or []))
    return json.dumps({"name": args.series,
                       "since": args.since if since is None else since,
                       "labels": labels, "agg": args.agg,
                       "step": args.step})


def _fmt_labels(labels: dict) -> str:
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return "{" + inner + "}" if inner else ""


def _coarse_tier_hint(hits) -> str:
    """One-line hint when a queried window exists ONLY in the TSDB's
    downsampled tier (every returned point is a coarse bucket): the
    output would otherwise silently show 10s buckets as raw samples."""
    if not hits:
        return ""
    if all(h.get("coarse_points", 0) and not h.get("hires_points", 1)
           for h in hits):
        return ("note: window predates the hi-res retention — showing "
                "downsampled buckets (use --agg min/max/avg to pick how "
                "they collapse)")
    return ""


def cmd_metrics(args):
    """Time-series observability CLI over the head TSDB (list series,
    tail one live, dump history as CSV)."""
    address = args.address or _auto_address()
    if args.action == "list":
        for s in _metrics_kv(address, "series"):
            print(f"{s['name']}{_fmt_labels(s['labels'])}  "
                  f"points={s['points']}  last={s['last_value']:g}")
        return
    if args.action == "tail":
        if not args.series:
            raise SystemExit("metrics tail requires a series name")
        seen: dict = {}
        since = None  # full --since window once, then only fresh points
        hinted = False
        try:
            while True:
                hits = _metrics_kv(address,
                                   _metrics_query_key(args, since))
                if not hinted:
                    hint = _coarse_tier_hint(hits)
                    if hint:
                        print(hint, file=sys.stderr)
                    hinted = True
                # The newest bucket (resolution coalescing / the
                # trailing --agg step) may still be accumulating; the
                # ts-keyed dedup would freeze its FIRST partial value,
                # so hold points back until their bucket window has
                # passed (age-based, so a series that stops updating
                # still prints its final sample on a later poll).
                # --once keeps snapshot semantics.
                hold_s = (args.step or 10.0) if args.agg else 1.0
                closed_before = time.time() - hold_s
                for s in hits:
                    key = (s["name"], tuple(sorted(s["labels"].items())))
                    points = s["points"]
                    if not args.once:
                        points = [p for p in points
                                  if p[0] <= closed_before]
                    for ts, value in points:
                        if ts <= seen.get(key, 0.0):
                            continue
                        seen[key] = ts
                        stamp = time.strftime("%H:%M:%S",
                                              time.localtime(ts))
                        print(f"{stamp} {s['name']}"
                              f"{_fmt_labels(s['labels'])} {value:g}",
                              flush=True)
                if args.once:
                    return
                # Dedup absorbs the overlap; the window must also cover
                # the hold-back age or a held bucket never reappears.
                since = max(args.interval * 2 + 1,
                            hold_s + args.interval + 1)
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return
    # dump: CSV history for one series (or every series with no name).
    import csv

    out = open(args.output, "w", newline="") if args.output else sys.stdout
    try:
        w = csv.writer(out)
        w.writerow(["name", "labels", "ts", "value"])
        n = 0
        for s in _metrics_kv(address, _metrics_query_key(args)):
            labels = _fmt_labels(s["labels"])
            for ts, value in s["points"]:
                w.writerow([s["name"], labels, f"{ts:.3f}", value])
                n += 1
        print(f"wrote {n} samples", file=sys.stderr)
    finally:
        if args.output:
            out.close()


def cmd_profile(args):
    """On-demand XLA profiler capture plane (``_private/xla_monitor``):

    * ``capture`` publishes a capture command on the GCS PROFILE channel;
      every XLA-active process on the target node runs ``jax.profiler``
      for --duration seconds and registers its trace dir in the GCS.
    * ``list`` shows registered captures (trace dirs open in
      TensorBoard / xprof).
    * ``programs`` dumps the cost-analysis program registry
      (per-program FLOPs, bytes accessed, compile time, retraces).
    """
    from ray_tpu._private import xla_monitor

    address = args.address or _auto_address()
    if args.action == "capture":
        capture_id = xla_monitor.request_capture(
            address, node=args.node, duration_s=args.duration)
        print(f"capture {capture_id} requested "
              f"(node={args.node}, {args.duration:g}s)")
        if args.no_wait:
            return
        deadline = time.monotonic() + args.duration + args.wait_timeout
        done: dict = {}
        prev_seen = None
        while time.monotonic() < deadline:
            # One KV scan per poll serves both checks (the namespace can
            # hold hundreds of old captures; don't double the RPC load).
            mine = [e for e in xla_monitor.list_captures(address)
                    if e.get("capture_id") == capture_id]
            for e in mine:
                if e.get("status") in ("done", "failed", "busy"):
                    done[(e.get("node_id"), e.get("pid"))] = e
            # Terminal AND stable across two polls: a slow process may
            # not have registered anything yet when the first fast one
            # finishes — one quiet settle poll catches stragglers.
            seen = sorted((e.get("node_id"), e.get("pid"),
                           e.get("status")) for e in mine)
            if done and all(e.get("status") != "capturing"
                            for e in mine) and seen == prev_seen:
                break
            prev_seen = seen
            time.sleep(0.5)
        if not done:
            raise SystemExit(
                "no capture registered before the timeout — is any "
                "process on that node running XLA work? (the capture "
                "listener activates with the first instrumented "
                "compile)")
        for e in sorted(done.values(), key=lambda d: d.get("pid", 0)):
            line = (f"  {e['status']:8} node={e.get('node_id')} "
                    f"pid={e.get('pid')}")
            if e.get("trace_dir"):
                line += f"  {e['trace_dir']} ({e.get('files', 0)} files)"
            if e.get("error"):
                line += f"  {e['error']}"
            print(line)
        return
    if args.action == "programs":
        rows = xla_monitor.list_programs(address)
        if args.format == "json":
            print(json.dumps(rows, indent=2))
            return
        for e in rows:
            flops = e.get("flops")
            nbytes = e.get("bytes_accessed")
            print(f"{e.get('program', '?'):24} node={e.get('node_id')} "
                  f"pid={e.get('pid')} "
                  f"compiles={e.get('compiles', '?')} "
                  f"retraces={e.get('retraces', '?')} "
                  f"sig={e.get('signature')} "
                  f"compile={e.get('compile_seconds', 0):.3f}s "
                  f"flops={flops if flops is not None else '-'} "
                  f"bytes={nbytes if nbytes is not None else '-'}"
                  + ("  RETRACE" if e.get("retrace") else ""))
        return
    # list
    entries = xla_monitor.list_captures(address)
    if args.format == "json":
        print(json.dumps(entries, indent=2))
        return
    if not entries:
        print("no captures registered")
        return
    for e in entries:
        stamp = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0)))
        print(f"{stamp} {e.get('capture_id'):28} {e.get('status', '?'):9} "
              f"node={e.get('node_id')} pid={e.get('pid')} "
              f"{e.get('trace_dir', '')}")


def cmd_ckpt(args):
    """Checkpoint plane CLI:

    * ``list`` shows committed manifests — from the cluster KV when an
      address is reachable, or from ``--root`` (filesystem scan) for
      offline runs.
    * ``inspect`` dumps one step directory: commit status, shard files,
      and per-leaf shape/dtype/bytes/shard-count.
    """
    from ray_tpu.checkpoint import plane as ckpt_plane

    if args.action == "inspect":
        if not args.path:
            raise SystemExit("ckpt inspect needs a step directory path")
        info = ckpt_plane.inspect_dir(args.path)
        if args.format == "json":
            print(json.dumps(info, indent=2))
            return
        status = "committed" if info["committed"] else "UNCOMMITTED"
        print(f"{info['dir']}  [{status}]  "
              f"shard_files={info['num_shard_files']}")
        man = info["manifest"]
        if man:
            stamp = time.strftime("%Y-%m-%d %H:%M:%S",
                                  time.localtime(man.get("ts", 0)))
            print(f"  run={man.get('run')} step={man.get('step')} "
                  f"nprocs={man.get('nprocs')} bytes={man.get('bytes')} "
                  f"committed_by=proc{man.get('committed_by')} at {stamp}")
        for i, leaf in enumerate(info["leaves"]):
            print(f"  leaf[{i:3d}] shape={tuple(leaf['shape'])} "
                  f"dtype={leaf['dtype']} shards={leaf['shards']} "
                  f"bytes={leaf['bytes']}")
        return
    # list
    if args.root:
        manifests = ckpt_plane.list_checkpoints(args.root)
    else:
        manifests = ckpt_plane.list_manifests_kv(
            args.address or _auto_address())
    if args.format == "json":
        print(json.dumps(manifests, indent=2))
        return
    if not manifests:
        print("no committed checkpoints")
        return
    for m in manifests:
        stamp = time.strftime("%H:%M:%S", time.localtime(m.get("ts", 0)))
        print(f"{stamp} run={m.get('run'):16} step={m.get('step'):>8} "
              f"nprocs={m.get('nprocs')} bytes={m.get('bytes')} "
              f"{m.get('dir', '')}")


def cmd_pool(args):
    """Chip-pool CLI: per-workload chip counts, live leases with
    deadlines, handoffs in flight with their state-machine stage, and
    the last SLO-guard reversal — straight from the ``__pool__`` KV
    journal (the ``ray-tpu ckpt list`` offline-friendly style)."""
    from ray_tpu.autoscaler.arbiter import TERMINAL, read_pool_state

    state = read_pool_state(args.address or _auto_address())
    if args.format == "json":
        print(json.dumps(state, indent=2))
        return
    alloc = state.get("allocation")
    if alloc is None:
        print("no chip pool (no arbiter has journaled a config)")
        return
    print(f"chips: serve={alloc['serve']} train={alloc['train']} "
          f"in_flight={alloc['in_flight']} / total={alloc['total']}")
    rev = state.get("last_reversal")
    if rev:
        stamp = time.strftime("%H:%M:%S", time.localtime(rev.get("ts", 0)))
        print(f"last SLO-guard {rev.get('action')}: {rev.get('signal')} "
              f"on {rev.get('lease_id')} ({rev.get('direction')}, "
              f"{rev.get('chips')} chips) at {stamp} "
              f"{rev.get('detail', '')}")
    leases = state.get("leases") or []
    if not leases:
        print("no leases")
        return
    for lease in leases:
        flight = "" if lease["stage"] in TERMINAL else "  [in flight]"
        deadline = ""
        if lease.get("deadline_ts"):
            deadline = "  deadline=" + time.strftime(
                "%H:%M:%S", time.localtime(lease["deadline_ts"]))
        since = time.strftime(
            "%H:%M:%S", time.localtime(lease["history"][-1][1]))
        print(f"{lease['lease_id']}  {lease['donor']}->"
              f"{lease['recipient']}  chips={lease['chips']:<3} "
              f"{lease['stage']:<15} since {since}{deadline}{flight}")


def cmd_head(args):
    """``ray-tpu head top``: sorted live view of where the single head
    process's capacity goes. Rates are window deltas over the
    head-sampled TSDB series, so it works against any running cluster
    with no support beyond the metric plane: KV ops+bytes/s by
    namespace, pubsub publish rates / fan-out latency / slow-subscriber
    drops, WAL queue+watermark health, and the gRPC saturation signals
    (queue-wait, occupancy, active streams)."""
    address = args.address or _auto_address()

    def snapshot():
        idx: dict = {}
        for prefix in ("ray_tpu_gcs_*", "ray_tpu_rpc_*"):
            for s in _metrics_kv(address, json.dumps(
                    {"name": prefix, "since": args.since})):
                idx.setdefault(s["name"], []).append(s)
        return idx

    def win(points):
        """(window delta, window seconds, last value) for one series."""
        if not points:
            return 0.0, 0.0, 0.0
        if len(points) == 1:
            return 0.0, 0.0, points[0][1]
        dv = max(points[-1][1] - points[0][1], 0.0)  # restart clamp
        dt = points[-1][0] - points[0][0]
        return dv, (dt if dt > 0 else 0.0), points[-1][1]

    def rate(points):
        dv, dt, _ = win(points)
        return dv / dt if dt else 0.0

    def rollup(idx, name, keys):
        """tag-tuple -> (summed window rate, summed last value),
        grouped by ``keys`` across pushing processes."""
        out: dict = {}
        for s in idx.get(name, ()):
            k = tuple(s["labels"].get(t, "") for t in keys)
            r, last = out.get(k, (0.0, 0.0))
            out[k] = (r + rate(s["points"]), last + win(s["points"])[2])
        return out

    def mean_ms(idx, hist, keys):
        """Windowed histogram mean (ms) per tag-tuple: rate(_sum) /
        rate(_count); lifetime mean when the window saw nothing."""
        sums = rollup(idx, hist + "_sum", keys)
        counts = rollup(idx, hist + "_count", keys)
        out = {}
        for k, (cr, clast) in counts.items():
            sr, slast = sums.get(k, (0.0, 0.0))
            if cr > 0:
                out[k] = sr / cr * 1000.0
            elif clast > 0:
                out[k] = slast / clast * 1000.0
        return out

    def section(title, rows):
        if not rows:
            return
        print(title)
        rows.sort(key=lambda r: -r[0])
        for _, line in rows[:args.limit]:
            print(line)

    def show(idx):
        print(f"head top @ {time.strftime('%H:%M:%S')}  "
              f"(rate window {args.since:g}s)")
        ops = rollup(idx, "ray_tpu_gcs_kv_ops_total", ("namespace", "op"))
        byts = rollup(idx, "ray_tpu_gcs_kv_bytes_total",
                      ("namespace", "op"))
        section("kv (ops/s by namespace):", [
            (r, f"  {ns:<14} {op:<5} {r:9.1f} ops/s "
                f"{byts.get((ns, op), (0.0, 0.0))[0]:12,.0f} B/s  "
                f"(lifetime {total:,.0f} ops)")
            for (ns, op), (r, total) in ops.items()])
        pub = rollup(idx, "ray_tpu_gcs_pubsub_published_total",
                     ("channel",))
        fan = mean_ms(idx, "ray_tpu_gcs_pubsub_fanout_seconds",
                      ("channel",))
        depth = rollup(idx, "ray_tpu_gcs_pubsub_queue_depth", ("channel",))
        section("pubsub (published/s by channel):", [
            (r, f"  {ch:<14} {r:9.1f} msg/s  "
                f"fanout {fan.get((ch,), 0.0):8.2f} ms  "
                f"queue {depth.get((ch,), (0, 0))[1]:.0f}")
            for (ch,), (r, _t) in pub.items()])
        drops = rollup(idx, "ray_tpu_gcs_pubsub_dropped_total",
                       ("channel", "subscriber"))
        section("pubsub drops (slow subscribers):", [
            (total, f"  {ch:<14} {sub:<24} dropped {total:,.0f} "
                    f"({r:.1f}/s)")
            for (ch, sub), (r, total) in drops.items() if total > 0])
        lag = rollup(idx, "ray_tpu_gcs_wal_watermark_lag", ("backend",))
        fsync = mean_ms(idx, "ray_tpu_gcs_wal_fsync_seconds", ("backend",))
        touts = rollup(idx, "ray_tpu_gcs_wal_sync_timeouts_total",
                       ("backend",))
        section("wal:", [
            (lg, f"  {be:<20} watermark lag {lg:6.0f}  "
                 f"fsync {fsync.get((be,), 0.0):8.2f} ms  "
                 f"sync timeouts {touts.get((be,), (0, 0))[1]:.0f}")
            for (be,), (_r, lg) in lag.items()])
        qwait = mean_ms(idx, "ray_tpu_rpc_queue_wait_seconds",
                        ("service",))
        occ = rollup(idx, "ray_tpu_rpc_executor_occupancy", ("service",))
        section("rpc (queue-wait by service):", [
            (ms, f"  {svc:<20} queue-wait {ms:8.2f} ms  "
                 f"occupancy {occ.get((svc,), (0, 0))[1]:.2f}")
            for (svc,), ms in qwait.items()])
        streams = rollup(idx, "ray_tpu_rpc_active_streams",
                         ("service", "method"))
        section("rpc streams:", [
            (n, f"  {svc}.{meth:<18} active {n:.0f}")
            for (svc, meth), (_r, n) in streams.items() if n > 0])
        retries = rollup(idx, "ray_tpu_rpc_client_retries_total",
                         ("service", "method", "reason"))
        section("client retries:", [
            (total, f"  {svc}.{meth} [{reason}]  {total:,.0f} ({r:.1f}/s)")
            for (svc, meth, reason), (r, total) in retries.items()
            if total > 0])

    try:
        while True:
            show(snapshot())
            if args.once:
                return
            time.sleep(args.interval)
            print()
    except KeyboardInterrupt:
        return


def cmd_logs(args):
    """Tail cluster logs (reference: ``ray logs`` + the dashboard log
    viewer over the LOG pubsub channel)."""
    if args.job:
        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient(args.address or _auto_address())
        print(client.get_job_logs(args.job), end="")
        return
    import pickle

    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    gcs = rpc.get_stub("GcsService", args.address or _auto_address())
    # The tail is bounded with a gRPC deadline (the stream blocks between
    # messages, so a wall-clock check alone would never fire). The Stub
    # treats timeout=None as "use the 30s default", so --follow passes an
    # explicit year-long deadline.
    stream = gcs.Subscribe(
        pb.SubscribeRequest(channels=["LOG"],
                            subscriber_id=f"cli-{os.getpid()}"),
        timeout=365 * 86400.0 if args.follow else args.duration)
    try:
        for msg in stream:
            try:
                rec = pickle.loads(msg.data)
            except Exception:  # noqa: BLE001
                continue
            for line in rec.get("lines", ()):
                print(f"[{rec.get('name', '?')} pid={rec.get('pid')}] {line}")
    except KeyboardInterrupt:
        pass
    except Exception as e:  # noqa: BLE001
        # Deadline expiry is how a non-follow tail ends; anything else
        # (dead GCS, dropped stream) must not exit 0 silently.
        if args.follow:
            raise SystemExit(f"log stream ended: {e}")
        if "deadline" not in str(e).lower():
            raise SystemExit(f"log stream failed: {e}")


def cmd_health_check(args):
    """Exit 0 when the GCS answers (reference: ``ray health-check``)."""
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    try:
        gcs = rpc.get_stub("GcsService", args.address or _auto_address())
        nodes = gcs.GetNodes(pb.GetNodesRequest(), timeout=5).nodes
    except Exception as e:  # noqa: BLE001
        print(f"unhealthy: {e}")
        raise SystemExit(1)
    alive = sum(n.alive for n in nodes)
    print(f"healthy: {alive}/{len(nodes)} nodes alive")
    if args.min_nodes and alive < args.min_nodes:
        raise SystemExit(1)


def cmd_stack(args):
    """Dump stack traces of live actor workers (reference: ``ray stack``)."""
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    targets = {}
    gcs = rpc.get_stub("GcsService", args.address or _auto_address())
    for a in gcs.ListActors(pb.ListActorsRequest(all_namespaces=True)).actors:
        if a.state == "ALIVE" and a.address:
            targets[a.actor_id.hex()[:12] + " " + a.class_name] = a.address
    if not targets:
        print("no live actor workers")
        return
    for name, addr in targets.items():
        print(f"=== {name} @ {addr} ===")
        try:
            stub = rpc.get_stub("WorkerService", addr)
            reply = stub.Stacktrace(pb.WorkerStacktraceRequest(), timeout=5)
            print(reply.stacktrace)
        except Exception as e:  # noqa: BLE001
            print(f"  <unreachable: {e}>")


def cmd_up(args):
    """Launch a cluster from a YAML config (reference: ``ray up`` +
    the cluster launcher). Single-host: the head plus min_workers worker
    node-manager processes start locally; with ``autoscaling: true`` a
    monitor process scales workers between min and max."""
    import yaml

    with open(args.config) as f:
        cfg = yaml.safe_load(f) or {}
    os.makedirs(STATE_DIR, exist_ok=True)
    env = dict(os.environ)

    gcs = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs.server", "--port",
         str(cfg.get("gcs_port", 0))],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
    _save_pid(gcs.pid)
    address = f"127.0.0.1:{_read_port(gcs, 'GCS_PORT')}"
    with open(ADDRESS_FILE, "w") as f:
        f.write(address)
    print(f"GCS started at {address}")

    from ray_tpu.autoscaler import LocalNodeProvider

    head_cfg = cfg.get("head", {"resources": {"CPU": float(
        os.cpu_count() or 4)}})
    provider = LocalNodeProvider(address)

    def _launch(node_cfg):
        nid = provider.create_node(node_cfg or {})
        # Record the pid IMMEDIATELY: a later launch failing must not
        # leave already-started nodes invisible to `ray-tpu down`.
        _save_pid(provider._procs[nid].pid)
        return nid

    _launch(head_cfg)
    print("head node started")
    if not cfg.get("autoscaling"):
        # With autoscaling the MONITOR owns the workers (its provider
        # enforces min_workers); pre-spawning here would double-provision
        # and leave unmanaged nodes the scaler can never scale down.
        for _ in range(int(cfg.get("min_workers", 0))):
            _launch(cfg.get("worker", {}))
        if cfg.get("min_workers"):
            print(f"{cfg['min_workers']} worker node(s) started")

    if cfg.get("autoscaling"):
        monitor_cfg = json.dumps({
            "worker": cfg.get("worker", {}),
            "provider": cfg.get("provider", {}),
            "min_workers": cfg.get("min_workers", 0),
            "max_workers": cfg.get("max_workers", 4),
            "idle_timeout_s": cfg.get("idle_timeout_s", 60.0),
        })
        mon = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.autoscaler.monitor",
             "--gcs-address", address, "--config", monitor_cfg],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
        _save_pid(mon.pid)
        print("autoscaler monitor started")
    if cfg.get("dashboard", True):
        # The dashboard runs as its own subprocess: an in-CLI thread
        # would die the moment `up` returns.
        dash = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.dashboard",
             "--gcs-address", address,
             "--port", str(cfg.get("dashboard_port", 8265))],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        _save_pid(dash.pid)
        dash_port = _read_port(dash, "DASHBOARD_PORT")
        print(f"Dashboard at http://127.0.0.1:{dash_port}")
    print(f"\nConnect with: ray_tpu.init(address={address!r})")
    if cfg.get("block"):
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass


def cmd_down(args):
    """Tear the launched cluster down (reference: ``ray down``)."""
    cmd_stop(args)


def cmd_gateway(args):
    """Serve the cross-language client gateway (C++ API / thin remote
    clients; reference: the Ray Client server)."""
    from ray_tpu.cross_language import ClientGateway

    gw = ClientGateway(args.address or _auto_address(), port=args.port)
    print(f"GATEWAY_PORT={gw.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        gw.stop()


def cmd_serve(args):
    """Declarative Serve control (reference: ``serve deploy/status``)."""
    import ray_tpu
    from ray_tpu import serve

    _connect(args)
    if args.action == "deploy":
        if not args.config:
            raise SystemExit("serve deploy requires a config file")
        # Apps import relative to the config's directory and the cwd.
        sys.path.insert(0, os.path.dirname(os.path.abspath(args.config)))
        sys.path.insert(0, os.getcwd())
        names = serve.deploy_config_file(args.config)
        print(f"deployed: {', '.join(names)}")
    elif args.action == "status":
        from ray_tpu.serve.api import CONTROLLER_NAME

        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            deployments = ray_tpu.get(
                controller.list_deployments.remote(), timeout=10)
        except ValueError:
            print("serve is not running")
            return
        for name in deployments:
            replicas = ray_tpu.get(
                controller.get_replicas.remote(name), timeout=10)
            print(f"{name}: {len(replicas)} replica(s)")
    else:
        serve.shutdown()
        print("serve shut down")


def cmd_resources(args):
    import ray_tpu

    _connect(args)
    print("total:", json.dumps(ray_tpu.cluster_resources(), sort_keys=True))
    print("avail:", json.dumps(ray_tpu.available_resources(),
                               sort_keys=True))


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start cluster processes on this host")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float)
    p.add_argument("--num-tpus", type=float, default=None,
                   help="unset = auto-detect; 0 = no TPU resources")
    p.add_argument("--resources", help='JSON, e.g. \'{"special": 2}\'')
    p.add_argument("--labels", help='JSON, e.g. \'{"tpu-slice": "s0"}\'')
    p.add_argument("--dashboard", action="store_true", default=True)
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument("--ray-client-server-port", type=int, default=10001,
                   help="ray:// driver proxy port (reference default "
                        "10001); -1 disables the proxy")
    p.add_argument("--block", action="store_true")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop all local cluster processes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="show cluster status")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("submit", help="submit a job entrypoint")
    p.add_argument("--address")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600)
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("jobs", help="list jobs")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("timeline",
                       help="dump a chrome-trace of cluster task events")
    p.add_argument("--address")
    p.add_argument("--output", "-o")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("trace",
                       help="path traces: 'trace request <id>' dumps one "
                            "serve request's chrome-trace timeline, "
                            "'trace train <run>' one training run's "
                            "(attempts, step windows, elastic "
                            "recoveries); requires RAY_TPU_TRACING=1")
    p.add_argument("kind", choices=["request", "train"],
                   help="what to trace: one serve request, or one "
                        "training run")
    p.add_argument("id",
                   help="request id (x-request-id) / trace id, or the "
                        "training run name (RunConfig.name)")
    p.add_argument("--address")
    p.add_argument("--output", "-o",
                   help="chrome-trace JSON path (default: "
                        "ray-tpu-trace-<id>.json)")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("why",
                       help="causal narrative from the flight recorder: "
                            "'why request|run|lease|node <id>' walks "
                            "control-plane cause links across planes "
                            "(chaos injection -> preemption notice -> "
                            "drain -> resume -> lease reversal) and "
                            "joins any tracing spans for the subject")
    p.add_argument("kind", choices=["request", "run", "lease", "node"],
                   help="subject kind the id names")
    p.add_argument("id",
                   help="request id / run name / lease id / node id")
    p.add_argument("--address")
    p.add_argument("--output", "-o",
                   help="also write the chain (events + spans) as JSON")
    p.set_defaults(fn=cmd_why)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("kind", choices=["nodes", "actors", "tasks", "objects",
                                    "placement-groups", "cluster-events"])
    p.add_argument("--address")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--format", choices=["table", "json"], default="table")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("memory", help="cluster object-store memory report")
    p.add_argument("--address")
    p.add_argument("--limit", type=int, default=50)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("metrics",
                       help="cluster time-series: list/tail/dump")
    p.add_argument("action", choices=["list", "tail", "dump"])
    p.add_argument("series", nargs="?",
                   help="series name (exact, or prefix ending with *)")
    p.add_argument("--address")
    p.add_argument("--label", action="append", metavar="K=V",
                   help="label filter, repeatable")
    p.add_argument("--since", type=float, default=600.0,
                   help="history window in seconds (default 600)")
    p.add_argument("--agg", choices=["avg", "min", "max", "sum", "last"])
    p.add_argument("--step", type=float,
                   help="aggregation bucket seconds (with --agg)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="tail poll period")
    p.add_argument("--once", action="store_true",
                   help="tail: print current window and exit")
    p.add_argument("--output", "-o", help="dump: CSV path (default stdout)")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("profile",
                       help="XLA profiler captures: capture/list/programs")
    p.add_argument("action", choices=["capture", "list", "programs"])
    p.add_argument("--address")
    p.add_argument("--node", default="*",
                   help="target node id (prefix ok; default: every node)")
    p.add_argument("--duration", type=float, default=2.0,
                   help="trace capture seconds (default 2)")
    p.add_argument("--no-wait", action="store_true",
                   help="capture: publish the command and exit")
    p.add_argument("--wait-timeout", type=float, default=30.0,
                   help="capture: extra seconds to wait for registration")
    p.add_argument("--format", choices=["table", "json"], default="table")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("ckpt",
                       help="checkpoint plane: list committed manifests, "
                            "inspect a step dir")
    p.add_argument("action", choices=["list", "inspect"])
    p.add_argument("path", nargs="?",
                   help="inspect: a step-<n> checkpoint directory")
    p.add_argument("--address")
    p.add_argument("--root",
                   help="list: scan this checkpoint root on disk instead "
                        "of the cluster KV")
    p.add_argument("--format", choices=["table", "json"], default="table")
    p.set_defaults(fn=cmd_ckpt)

    p = sub.add_parser("pool",
                       help="chip pool: per-workload chips, live leases, "
                            "handoffs in flight, last SLO reversal")
    p.add_argument("action", choices=["status"])
    p.add_argument("--address")
    p.add_argument("--format", choices=["table", "json"], default="table")
    p.set_defaults(fn=cmd_pool)

    p = sub.add_parser("head",
                       help="head control-plane load: KV by namespace, "
                            "pubsub fan-out, WAL health, RPC saturation")
    p.add_argument("action", choices=["top"])
    p.add_argument("--address")
    p.add_argument("--since", type=float, default=60.0,
                   help="rate window seconds (default 60)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--limit", type=int, default=30,
                   help="max rows per section (default 30)")
    p.set_defaults(fn=cmd_head)

    p = sub.add_parser("logs", help="tail worker logs (or one job's logs)")
    p.add_argument("--address")
    p.add_argument("--job", help="print this job's captured logs and exit")
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--duration", type=float, default=2.0,
                   help="seconds to tail when not following")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("health-check", help="probe the GCS; exit 0 if healthy")
    p.add_argument("--address")
    p.add_argument("--min-nodes", type=int, default=0)
    p.set_defaults(fn=cmd_health_check)

    p = sub.add_parser("stack", help="dump live actor worker stack traces")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("resources", help="cluster total/available resources")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_resources)

    p = sub.add_parser("serve",
                       help="serve subcommands: deploy/status/shutdown")
    p.add_argument("action", choices=["deploy", "status", "shutdown"])
    p.add_argument("config", nargs="?", help="YAML config (deploy)")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("config")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down the launched cluster")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("gateway",
                       help="serve the cross-language client gateway")
    p.add_argument("--address")
    p.add_argument("--port", type=int, default=0)
    p.set_defaults(fn=cmd_gateway)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
