"""ray_tpu CLI (reference: ``python/ray/scripts/scripts.py`` — ray
start/stop/status/submit/...). Run as ``python -m ray_tpu.scripts.cli``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

STATE_DIR = "/tmp/ray_tpu"
ADDRESS_FILE = os.path.join(STATE_DIR, "address")
PIDS_FILE = os.path.join(STATE_DIR, "pids")


def _save_pid(pid: int):
    os.makedirs(STATE_DIR, exist_ok=True)
    with open(PIDS_FILE, "a") as f:
        f.write(f"{pid}\n")


def _read_port(proc, tag: str, timeout_s: float = 30.0) -> int:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline().decode().strip()
        if line.startswith(f"{tag}="):
            return int(line.split("=", 1)[1])
        if not line and proc.poll() is not None:
            break
    raise RuntimeError(f"failed to read {tag} from subprocess")


def cmd_start(args):
    os.makedirs(STATE_DIR, exist_ok=True)
    env = dict(os.environ)
    if args.head:
        gcs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs.server",
             "--port", str(args.port or 0)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        _save_pid(gcs.pid)
        gcs_port = _read_port(gcs, "GCS_PORT")
        address = f"127.0.0.1:{gcs_port}"
        with open(ADDRESS_FILE, "w") as f:
            f.write(address)
        print(f"GCS started at {address}")
    else:
        address = args.address or _auto_address()

    nm_cmd = [sys.executable, "-m", "ray_tpu._private.node_manager.server",
              "--gcs-address", address,
              "--num-cpus", str(args.num_cpus or os.cpu_count())]
    if args.num_tpus:
        nm_cmd += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        nm_cmd += ["--resources", args.resources]
    if args.labels:
        nm_cmd += ["--labels", args.labels]
    nm = subprocess.Popen(nm_cmd, stdout=subprocess.PIPE,
                          stderr=subprocess.DEVNULL, env=env)
    _save_pid(nm.pid)
    nm_port = _read_port(nm, "NODE_PORT")
    print(f"Node manager started at 127.0.0.1:{nm_port}")

    if args.head and args.dashboard:
        from ray_tpu.dashboard import Dashboard

        dash = Dashboard(address, port=args.dashboard_port)
        print(f"Dashboard at http://127.0.0.1:{dash.port}")
        print(f"\nConnect with: ray_tpu.init(address={address!r})")
        print("Press Ctrl-C to keep running in foreground, or re-run with "
              "--block to stay attached.")
        if args.block:
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                pass
    elif args.head:
        print(f"\nConnect with: ray_tpu.init(address={address!r})")


def _auto_address() -> str:
    if os.environ.get("RAY_TPU_ADDRESS"):
        return os.environ["RAY_TPU_ADDRESS"]
    if os.path.exists(ADDRESS_FILE):
        with open(ADDRESS_FILE) as f:
            return f.read().strip()
    raise SystemExit("no cluster address: pass --address or start a head")


def cmd_stop(args):
    if not os.path.exists(PIDS_FILE):
        print("nothing to stop")
        return
    with open(PIDS_FILE) as f:
        pids = [int(line) for line in f if line.strip()]
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"stopped {pid}")
        except OSError:
            pass
    os.remove(PIDS_FILE)
    if os.path.exists(ADDRESS_FILE):
        os.remove(ADDRESS_FILE)


def cmd_status(args):
    address = args.address or _auto_address()
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    gcs = rpc.get_stub("GcsService", address)
    nodes = gcs.GetNodes(pb.GetNodesRequest()).nodes
    print(f"Cluster at {address}: "
          f"{sum(n.alive for n in nodes)}/{len(nodes)} nodes alive")
    for n in nodes:
        state = "ALIVE" if n.alive else "DEAD"
        print(f"  {n.node_id[:12]} {state:6} {n.address:22} "
              f"resources={dict(n.resources)}")
    actors = gcs.ListActors(pb.ListActorsRequest(all_namespaces=True)).actors
    if actors:
        print(f"Actors ({len(actors)}):")
        for a in actors:
            print(f"  {a.actor_id.hex()[:12]} {a.state:10} {a.class_name}")


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    address = args.address or _auto_address()
    client = JobSubmissionClient(address)
    job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
    print(f"submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout_s=args.timeout)
        print(f"{job_id}: {status}")
        print(client.get_job_logs(job_id))
        if status != "SUCCEEDED":
            raise SystemExit(1)


def cmd_jobs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address or _auto_address())
    for info in client.list_jobs():
        if info:
            print(f"{info['job_id']:32} {info['status']:10} "
                  f"{info['entrypoint']}")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start cluster processes on this host")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float)
    p.add_argument("--num-tpus", type=float, default=0)
    p.add_argument("--resources", help='JSON, e.g. \'{"special": 2}\'')
    p.add_argument("--labels", help='JSON, e.g. \'{"tpu-slice": "s0"}\'')
    p.add_argument("--dashboard", action="store_true", default=True)
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument("--block", action="store_true")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop all local cluster processes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="show cluster status")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("submit", help="submit a job entrypoint")
    p.add_argument("--address")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600)
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("jobs", help="list jobs")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_jobs)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
