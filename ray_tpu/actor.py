"""ActorClass / ActorHandle: the actor frontend.

Re-design of the reference actor API (reference: ``python/ray/actor.py`` —
``ActorClass`` :602, ``ActorClass._remote`` :890, ``ActorHandle`` :1265):
``@ray_tpu.remote`` on a class yields an :class:`ActorClass`;
``.remote(*args)`` creates the actor through the core runtime and returns an
:class:`ActorHandle` whose attribute access yields :class:`ActorMethod`
proxies submitting ordered actor tasks.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as _worker
from ray_tpu._private.ids import ActorID
from ray_tpu._private.options import RemoteOptions, is_streaming


def method(**method_options):
    """Decorator for actor methods: ``@ray_tpu.method(num_returns=2)``
    (reference: ``python/ray/actor.py::method``)."""

    def decorator(m):
        m.__ray_tpu_method_options__ = method_options
        return m

    return decorator


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 method_options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._method_options = method_options or {}

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly. "
            f"Use .{self._method_name}.remote() instead.")

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs)

    def options(self, **overrides):
        new = ActorMethod(self._handle, self._method_name,
                          {**self._method_options, **overrides})
        return new

    def _remote(self, args, kwargs):
        opts = self._handle._options.merged_with(
            {k: v for k, v in self._method_options.items()
             if k in ("num_returns",)})
        refs = _worker.global_worker().core.submit_actor_task(
            self._handle._actor_id, self._method_name, args, kwargs, opts)
        # Same source of truth as submit_actor_task: the merged options
        # (a class-level num_returns="streaming" must stream too).
        num_returns = opts.num_returns
        if is_streaming(num_returns):
            from ray_tpu._private.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0],
                                      owner_address=refs[0].owner_address())
        if num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls: type, options: RemoteOptions):
        self._actor_id = actor_id
        self._cls = cls
        self._options = options
        self._method_option_map = {
            name: getattr(m, "__ray_tpu_method_options__")
            for name, m in vars(cls).items()
            if callable(m) and hasattr(m, "__ray_tpu_method_options__")
        }

    @classmethod
    def _from_actor_id(cls, actor_id, actor_cls, options):
        return cls(actor_id, actor_cls, options)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if not hasattr(self._cls, name):
            raise AttributeError(
                f"Actor class {self._cls.__name__!r} has no method {name!r}")
        return ActorMethod(self, name, self._method_option_map.get(name))

    def __repr__(self):
        return (f"ActorHandle({self._cls.__name__}, "
                f"{self._actor_id.hex()[:16]})")

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id.binary(), self._cls, self._options))

    def _actor_state(self):
        core = _worker.global_worker().core
        state = getattr(core, "actor_state", None)
        return state(self._actor_id) if state else {}


def _rebuild_handle(actor_id_binary: bytes, cls, options) -> ActorHandle:
    return ActorHandle(ActorID(actor_id_binary), cls, options)


class ActorClass:
    def __init__(self, cls: type, options: RemoteOptions):
        self._cls = cls
        self._options = options
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated directly. "
            f"Use {self._cls.__name__}.remote() instead.")

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._options)

    def options(self, **option_overrides) -> "ActorClass":
        new = ActorClass.__new__(ActorClass)
        new._cls = self._cls
        new._options = self._options.merged_with(option_overrides)
        functools.update_wrapper(new, self._cls, updated=[])
        return new

    def _remote(self, args, kwargs, options: RemoteOptions) -> ActorHandle:
        import dataclasses

        from ray_tpu._private.concurrency import class_is_async

        options = dataclasses.replace(
            options, _is_async_actor=class_is_async(self._cls))
        core = _worker.global_worker().core
        if options.name and options.get_if_exists:
            try:
                actor_id, cls, opts = core.get_named_actor(
                    options.name, options.namespace)
                return ActorHandle(actor_id, cls, opts)
            except ValueError:
                pass
        actor_id = core.create_actor(self._cls, args, kwargs, options)
        return ActorHandle(actor_id, self._cls, options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    @property
    def cls(self):
        return self._cls


def exit_actor():
    """Called inside an actor method to terminate the actor after this call
    (reference: ``ray.actor.exit_actor``)."""
    from ray_tpu import exceptions

    raise exceptions.AsyncioActorExit("exit_actor() called")
