"""Offline LLM batch inference over ray_tpu.data pipelines.

Reference: ``python/ray/llm/_internal/batch`` — processors that run an LLM
over a Dataset with a pool of engine-owning actors. Here each pool actor
owns a :class:`~ray_tpu.models.continuous_batching.ContinuousBatcher`
(compiled prefill/decode with slot-pooled KV cache, built ONCE per actor):
every incoming Data batch submits all its prompts together and the batcher
runs them to completion with continuous slot reuse, so short prompts don't
wait for long ones.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.models import llama


class LLMBatchWorker:
    """Stateful ``map_batches`` UDF: one compiled batcher per pool actor."""

    def __init__(self, config: llama.LlamaConfig, params=None,
                 max_new_tokens: int = 32, num_slots: int = 8,
                 max_len: int = 256, eos_token: Optional[int] = None,
                 input_column: str = "prompt_ids",
                 output_column: str = "generated_ids"):
        import ray_tpu
        from ray_tpu.models.continuous_batching import ContinuousBatcher

        if isinstance(params, ray_tpu.ObjectRef):
            params = ray_tpu.get(params)
        self.batcher = ContinuousBatcher(config, params=params,
                                         num_slots=num_slots,
                                         max_len=max_len,
                                         eos_token=eos_token)
        self.max_new_tokens = max_new_tokens
        self.input_column = input_column
        self.output_column = output_column

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        prompts = [list(map(int, p)) for p in batch[self.input_column]]
        rids = [self.batcher.submit(p, self.max_new_tokens)
                for p in prompts]
        results = self.batcher.run_to_completion()
        out = dict(batch)
        out[self.output_column] = [results[rid] for rid in rids]
        return out


def batch_generate(ds, config: llama.LlamaConfig, *, params=None,
                   concurrency: int = 1, max_new_tokens: int = 32,
                   num_slots: int = 8, max_len: int = 256,
                   eos_token: Optional[int] = None,
                   input_column: str = "prompt_ids",
                   output_column: str = "generated_ids"):
    """Run greedy generation over a Dataset of token-id prompts.

    Returns a Dataset with ``output_column`` holding generated token ids
    (reference: the build_llm_processor entry of ``llm/_internal/batch``).
    ``concurrency`` engine actors each compile the model once and stream
    the dataset's blocks through their continuous batcher. Params ship
    through the object store (one put, fetched per actor) instead of
    being pickled into the plan once per actor.
    """
    import ray_tpu

    if params is not None and not isinstance(params, ray_tpu.ObjectRef) \
            and ray_tpu.is_initialized():
        params = ray_tpu.put(params)
    return ds.map_batches(
        LLMBatchWorker,
        concurrency=concurrency,
        fn_constructor_kwargs=dict(
            config=config, params=params, max_new_tokens=max_new_tokens,
            num_slots=num_slots, max_len=max_len, eos_token=eos_token,
            input_column=input_column, output_column=output_column),
    )
