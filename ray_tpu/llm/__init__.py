"""ray_tpu.llm: LLM serving + batch inference on ray_tpu serve.

Reference: ``python/ray/llm`` — vLLM-backed deployments
(``llm/_internal/serve``) and batch processors (``llm/_internal/batch``).
ray_tpu serves its own jit-compiled models (``ray_tpu.models.inference``)
instead of hosting an external engine: a deployment wraps a
``LlamaGenerator`` whose prefill/decode are one compiled program per shape,
with ``@serve.batch`` merging concurrent requests into one batched decode
(the continuous-batching analog at request granularity).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu import serve
from ray_tpu.models import llama
from ray_tpu.models.inference import LlamaGenerator


@serve.deployment
class LlamaDeployment:
    """Batched text-completion replica (token-id interface; tokenizers are
    the caller's concern, as in the reference's processor configs)."""

    def __init__(self, config: Optional[llama.LlamaConfig] = None,
                 params=None, max_len: int = 512,
                 max_batch_size: int = 8):
        self.config = config or llama.LlamaConfig.tiny()
        self.generator = LlamaGenerator(self.config, params=params,
                                        max_len=max_len)
        self.max_batch_size = max_batch_size

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    def __call__(self, requests: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        # Pad prompts to a common length, run one batched generate.
        prompts = [np.asarray(r["prompt_token_ids"], np.int32)
                   for r in requests]
        max_new = max(int(r.get("max_tokens", 16)) for r in requests)
        temperature = float(requests[0].get("temperature", 0.0))
        plen = max(len(p) for p in prompts)
        batch = np.zeros((len(prompts), plen), np.int32)
        for i, p in enumerate(prompts):
            batch[i, plen - len(p):] = p  # left-pad
        out = np.asarray(self.generator.generate(
            batch, max_new_tokens=max_new, temperature=temperature))
        return [
            {"token_ids": out[i, : int(r.get("max_tokens", 16))].tolist()}
            for i, r in enumerate(requests)
        ]


def build_llama_app(config: Optional[llama.LlamaConfig] = None,
                    num_replicas: int = 1, max_len: int = 512):
    dep = LlamaDeployment.options(num_replicas=num_replicas)
    return dep.bind(config, None, max_len)


__all__ = ["LlamaDeployment", "build_llama_app"]
