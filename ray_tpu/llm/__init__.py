"""ray_tpu.llm: LLM serving + batch inference on ray_tpu serve.

Reference: ``python/ray/llm`` — vLLM-backed deployments
(``llm/_internal/serve``) and batch processors (``llm/_internal/batch``).
ray_tpu serves its own jit-compiled models (``ray_tpu.models.inference``)
instead of hosting an external engine: a deployment wraps a
``LlamaGenerator`` whose prefill/decode are one compiled program per shape,
with ``@serve.batch`` merging concurrent requests into one batched decode
(the continuous-batching analog at request granularity).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu import serve
from ray_tpu.models import llama
from ray_tpu.models.inference import LlamaGenerator


@serve.deployment
class LlamaDeployment:
    """Batched text-completion replica (token-id interface; tokenizers are
    the caller's concern, as in the reference's processor configs)."""

    def __init__(self, config: Optional[llama.LlamaConfig] = None,
                 params=None, max_len: int = 512,
                 max_batch_size: int = 8,
                 checkpoint_path: Optional[str] = None):
        self.config = config or llama.LlamaConfig.tiny()
        if params is None and checkpoint_path:
            params = _params_from_checkpoint(checkpoint_path)
        self.generator = LlamaGenerator(self.config, params=params,
                                        max_len=max_len)
        self.max_batch_size = max_batch_size

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    def __call__(self, requests: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        # Pad prompts to a common length, run one batched generate.
        prompts = [np.asarray(r["prompt_token_ids"], np.int32)
                   for r in requests]
        max_new = max(int(r.get("max_tokens", 16)) for r in requests)
        temperature = float(requests[0].get("temperature", 0.0))
        plen = max(len(p) for p in prompts)
        batch = np.zeros((len(prompts), plen), np.int32)
        for i, p in enumerate(prompts):
            batch[i, plen - len(p):] = p  # left-pad
        out = np.asarray(self.generator.generate(
            batch, max_new_tokens=max_new, temperature=temperature))
        return [
            {"token_ids": out[i, : int(r.get("max_tokens", 16))].tolist()}
            for i, r in enumerate(requests)
        ]


def _params_from_checkpoint(path: str):
    """Cold-start params from a training run's committed checkpoint
    (checkpoint plane, ``ray_tpu/checkpoint/plane.py``): the newest
    committed manifest under ``path`` — a plane root, run dir, or
    anything ``load_latest`` accepts. A saved ``TrainState`` contributes
    its ``params``; a bare params pytree loads as-is. The serving mesh
    need not match the training topology (elastic restore)."""
    from ray_tpu.checkpoint import load_latest

    state = load_latest(path)
    return getattr(state, "params", state)


def build_llama_app(config: Optional[llama.LlamaConfig] = None,
                    num_replicas: int = 1, max_len: int = 512,
                    checkpoint_path: Optional[str] = None):
    dep = LlamaDeployment.options(num_replicas=num_replicas)
    return dep.bind(config, None, max_len,
                    checkpoint_path=checkpoint_path)


__all__ = ["LlamaDeployment", "build_llama_app"]


@serve.deployment
class ContinuousLlamaDeployment:
    """Continuous-batching completion replica (reference: the vLLM engine
    behind ``ray.serve.llm``): one shared slot pool per replica; requests
    join mid-flight and stream tokens as decode ticks produce them. Use
    with handle ``stream=True`` (or plain calls for full completions)."""

    def __init__(self, config: Optional[llama.LlamaConfig] = None,
                 params=None, num_slots: int = 8, max_len: int = 512,
                 eos_token: Optional[int] = None, sync_every: int = 1,
                 use_decode_kernel: Optional[bool] = None,
                 paged: Optional[bool] = None, block_size: int = 64,
                 kv_dtype: Optional[str] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 sampling=None,
                 spec_k: Optional[int] = None,
                 spec_draft_layers: Optional[int] = None,
                 spec_adaptive: Optional[bool] = None,
                 checkpoint_path: Optional[str] = None,
                 role: Optional[str] = None):
        """Engine knobs (``num_slots``, ``max_len``, ``sync_every``,
        ``use_decode_kernel``, and the paged-KV plane's ``paged`` /
        ``block_size`` / ``kv_dtype`` / ``num_blocks`` / ``sampling``)
        pass straight to the ContinuousBatcher and are overridable
        per-deploy via the serve config ``init_kwargs`` (see
        serve/config.py) — no application-module edits to retune a
        replica. ``sampling`` accepts a
        :class:`~ray_tpu.models.sampling.SamplingParams` or a plain dict
        (``{"temperature": 0.7, "top_p": 0.9, "seed": 0}``), which is
        what YAML-sourced deploy configs produce. ``checkpoint_path``
        cold-starts params from a training run's newest committed
        checkpoint (manifest plane).

        Speculative decoding rides the same path: ``spec_k`` (or
        ``RAY_TPU_SPEC_K``) enables draft-and-verify decode at depth k,
        ``spec_draft_layers`` sizes the truncated self-drafter, and
        ``spec_adaptive`` lets the accept-rate controller ladder k (down
        to 0 = the plain tick). All three are ordinary ``init_kwargs``
        overrides, so a YAML deploy config can turn speculation on per
        deployment.

        ``role`` (or ``RAY_TPU_SERVE_ROLE``) makes this a disaggregated
        replica: ``"prefill"`` replicas serve :meth:`prefill` (admission
        + paged prefill, then export the KV handoff), ``"decode"``
        replicas serve :meth:`decode_from` / :meth:`reserve_kv` (import
        the handoff and run the decode ticks) — plus every colocated
        entry point. The default ``"both"`` is the ordinary colocated
        engine."""
        import queue
        import threading
        import uuid

        from ray_tpu.models.continuous_batching import ContinuousBatcher

        self.config = config or llama.LlamaConfig.tiny()
        if params is None and checkpoint_path:
            params = _params_from_checkpoint(checkpoint_path)
        self._queues: Dict[int, "queue.Queue"] = {}
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._queue_mod = queue
        self.batcher = ContinuousBatcher(
            self.config, params=params, num_slots=num_slots,
            max_len=max_len, eos_token=eos_token,
            token_callback=self._on_token, sync_every=sync_every,
            use_decode_kernel=use_decode_kernel, paged=paged,
            block_size=block_size, kv_dtype=kv_dtype,
            num_blocks=num_blocks, prefix_cache=prefix_cache,
            sampling=sampling, spec_k=spec_k,
            spec_draft_layers=spec_draft_layers,
            spec_adaptive=spec_adaptive, role=role)
        # Reservation tickets are engine-local ids; the nonce scopes a
        # ticket to THIS replica so a router whose reserve and
        # decode_from calls landed on different replicas cannot spend
        # one replica's ticket against another's arena.
        self._nonce = uuid.uuid4().hex[:16]
        threading.Thread(target=self._tick_loop, daemon=True,
                         name="llm-ticks").start()

    def _on_token(self, rid: int, token: int) -> None:
        q = self._queues.get(rid)
        if q is not None:
            q.put(token)

    def _tick_loop(self) -> None:
        import logging

        log = logging.getLogger(__name__)
        while True:
            self._work.wait()
            try:
                with self._lock:
                    if not self.batcher.has_work():
                        self._work.clear()
                        continue
                    finished = self.batcher.step()
                for rid in finished:
                    q = self._queues.get(rid)
                    if q is not None:
                        q.put(None)  # end-of-stream
            except Exception as e:  # noqa: BLE001
                # Engine error (OOM, bad request reaching the kernel):
                # fail every in-flight stream explicitly and reset the
                # slot pool, instead of dying silently and leaving
                # clients blocked on their queues.
                log.exception("continuous-batching tick failed; "
                              "aborting in-flight requests")
                with self._lock:
                    self.batcher.reset()
                    queues = dict(self._queues)
                for q in queues.values():
                    q.put(e)

    @staticmethod
    def _request_trace() -> Optional[Dict[str, Any]]:
        """The serve request context of the CALLING request (set by the
        replica before user code runs; rides the contextvar through the
        sync executor hop), normalized into the engine's trace dict. The
        tenant falls back to the multiplexed model id so per-tenant
        TTFT/TPOT attribution works even for callers that built their
        own context."""
        from ray_tpu.serve import multiplex
        from ray_tpu.serve.context import get_request_context

        rctx = get_request_context()
        if rctx is None:
            return None
        trace = dict(rctx)
        trace.setdefault("tenant", multiplex.get_request_tenant())
        return trace

    def pressure(self) -> Dict[str, Any]:
        """Live engine pressure for the serve pressure endpoint (queue
        depth, KV blocks free, in-flight prefill tokens — the
        prefix/KV-pressure router's input). Under the engine lock: the
        snapshot iterates the waiting queue, which the tick thread
        mutates."""
        with self._lock:
            return self.batcher.pressure_snapshot()

    # ---------------------------------------- RL weight-sync plane (rl/)
    def weight_version(self) -> int:
        """Version of the params currently serving (0 = cold-start)."""
        return self.batcher.weight_version

    def swap_weights(self, weights, version: Optional[int] = None,
                     cause: str = "publish", manifest: Optional[dict] = None,
                     run: Optional[str] = None) -> int:
        """Swap the live params at a tick boundary.

        Taking ``self._lock`` IS the tick-boundary guarantee: the tick
        thread holds the same lock around ``batcher.step()``, so the swap
        lands strictly between ticks — in-flight requests keep their KV
        cache and continue under the new weights, un-dropped. Emits the
        ``rl.weight_swap`` flight event (caused by the trainer's publish
        event when a ``manifest`` is supplied, so ``ray-tpu why run``
        reconstructs the publish→swap chain) and counts the swap by
        cause. Returns the version now live."""
        import time as _time

        from ray_tpu._private import events as _events
        from ray_tpu._private import metrics_defs as mdefs

        manifest = manifest or {}
        run = run or manifest.get("run") or "rl"
        with self._lock:
            v = self.batcher.swap_params(weights, version=version)
        attrs = {"version": v, "swap_cause": cause}
        if manifest.get("ts"):
            # Trainer-publish → generator-live end-to-end latency.
            attrs["e2e_seconds"] = round(
                max(_time.time() - float(manifest["ts"]), 0.0), 6)
        _events.emit("rl.weight_swap", cause=manifest.get("event_id", ""),
                     subject={"run": run}, **attrs)
        mdefs.RL_SWAPS.inc(tags={"run": run, "cause": cause})
        mdefs.RL_VERSION.set(v, tags={"run": run, "role": "generator"})
        return v

    def enable_weight_sync(self, spec, run: str = "rl",
                           poll_s: float = 0.05,
                           target_shardings=None) -> None:
        """Start the subscriber poll thread: fast path reads the trainer's
        weight channel (``spec`` = a pickled channel reader attach-spec),
        and when the fast path breaks (writer gone, shed while lagging)
        the ladder falls back to the crc32-verified checkpoint manifest —
        both land through :meth:`swap_weights`, never mid-tick."""
        import logging
        import threading
        import time as _time

        from ray_tpu.rl.weight_sync import WeightSubscriber

        log = logging.getLogger(__name__)
        sub = (spec if isinstance(spec, WeightSubscriber)
               else WeightSubscriber(spec, run=run,
                                     target_shardings=target_shardings))
        self._subscriber = sub
        self._sync_stop = threading.Event()

        def _loop():
            while not self._sync_stop.is_set():
                try:
                    got = sub.poll(timeout=poll_s)
                except Exception:  # noqa: BLE001 — fast path down
                    try:
                        manifest, params = sub.restore_fallback()
                        if int(manifest["version"]) > \
                                self.batcher.weight_version:
                            self.swap_weights(
                                params, version=int(manifest["version"]),
                                cause="fallback", manifest=manifest,
                                run=run)
                    except Exception:  # noqa: BLE001
                        log.exception("rl: weight-sync fallback failed")
                    _time.sleep(max(poll_s, 0.05))
                    continue
                if got is None:
                    continue
                manifest, params = got
                self.swap_weights(params,
                                  version=int(manifest["version"]),
                                  cause="publish", manifest=manifest,
                                  run=run)

        t = threading.Thread(target=_loop, daemon=True,
                             name="rl-weight-sync")
        t.start()
        self._sync_thread = t

    def disable_weight_sync(self) -> None:
        stop = getattr(self, "_sync_stop", None)
        if stop is not None:
            stop.set()

    def score_logprobs(self, prompt_token_ids,
                       token_ids) -> List[float]:
        """Teacher-forced behavior logprobs of ``token_ids`` given
        ``prompt_token_ids`` under the CURRENT live params (the RL
        experience path's behavior policy). Under the engine lock so the
        params can't swap mid-score."""
        with self._lock:
            lp = self.batcher.score_logprobs(list(prompt_token_ids),
                                             list(token_ids))
        return [float(x) for x in lp]

    def generate(self, prompt_token_ids,
                 max_tokens: int = 16):
        """Streaming generator of token ids (serve stream=True surface).
        Accepts either the token-id list directly or the ingress payload
        dict (``{"prompt_token_ids": [...], "max_tokens": N}``) — the
        HTTP/gRPC streaming routes (``POST /<name>/stream/generate``)
        hand the whole JSON payload through as one argument, and the
        recovery journal resubmits exactly that payload shape.

        Chaos sites (``_private/chaos.py`` ``kill_replica``): before the
        engine submit (``phase=prefill`` — the request is queued-or-
        prefilling, nothing streamed) and before yielding the Nth token
        (``phase=decode,token=N`` — mid-decode, N tokens already
        streamed). The raised ``SimulatedProcessDeath`` unwinds through
        the replica actor's task machinery into genuine actor death —
        exactly what the ingress journal recovers from."""
        from ray_tpu._private import chaos

        resumed_tokens = 0
        if isinstance(prompt_token_ids, dict):
            payload = prompt_token_ids
            prompt_token_ids = payload["prompt_token_ids"]
            max_tokens = payload.get("max_tokens", max_tokens)
            resumed_tokens = int(payload.get("resumed_tokens", 0) or 0)
        if resumed_tokens and self.batcher.eos_token is not None \
                and prompt_token_ids \
                and prompt_token_ids[-1] == self.batcher.eos_token:
            # Mid-decode RESUME whose last already-delivered token was
            # EOS: the original generation had finished — only the
            # end-of-stream sentinel died with the replica. Decoding
            # the leftover budget would append post-EOS garbage the
            # un-killed run never produced. (Only resumes check this:
            # an ORIGINAL prompt may legitimately end with EOS.)
            return
        q = self._queue_mod.Queue()
        trace = self._request_trace()
        if chaos.enabled():
            chaos.inject("serve_replica", phase="prefill",
                         tokens=len(prompt_token_ids))
        with self._lock:
            rid = self.batcher.submit(list(prompt_token_ids),
                                      max_new_tokens=int(max_tokens),
                                      trace=trace)
            self._queues[rid] = q
        self._work.set()
        done = False
        emitted = 0
        try:
            while True:
                token = q.get(timeout=300)
                if token is None:
                    done = True
                    return
                if isinstance(token, Exception):
                    done = True
                    raise token
                if chaos.enabled():
                    # Fires BEFORE the yield: a rule with token=N dies
                    # with exactly N tokens delivered downstream.
                    chaos.inject("serve_replica", phase="decode",
                                 token=emitted)
                emitted += 1
                yield token
        finally:
            self._queues.pop(rid, None)
            if not done:
                # Abandoned stream (client disconnect or simulated
                # process death): free the slot so the ghost request
                # stops burning decode ticks.
                with self._lock:
                    self.batcher.cancel(rid)

    # ------------------------------------ disaggregated prefill/decode
    def _req_deployment(self) -> str:
        from ray_tpu.serve.context import get_request_context

        rctx = get_request_context()
        return (rctx or {}).get("deployment", "")

    def prefill(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Prefill-role unary: admission + paged prefill for the
        payload, then export the finished arena blocks as a KV handoff.
        Returns the transfer MANIFEST (staging bytes already staged in
        a shm channel; the manifest carries the reader attach-spec) —
        the router journals it and opens the decode stream. Requests
        that finish AT the first token (``max_tokens == 1``, an EOS
        first token, or a resumed prompt already ending in EOS) return
        ``{"done": [...]}`` instead: the whole completion happened
        here, nothing to hand off.

        Chaos: ``serve_replica``/``phase=prefill`` before the submit
        (nothing journaled — the router resubmits) and
        ``kv_transfer``/``stage=export`` inside the transfer helper
        (prefill death mid-export — same resubmit leg)."""
        from ray_tpu._private import chaos
        from ray_tpu.serve import kv_transfer

        prompt = list(payload["prompt_token_ids"])
        max_tokens = int(payload.get("max_tokens", 16))
        resumed_tokens = int(payload.get("resumed_tokens", 0) or 0)
        if resumed_tokens and self.batcher.eos_token is not None \
                and prompt and prompt[-1] == self.batcher.eos_token:
            # Mid-decode resume whose last delivered token was EOS: the
            # generation had finished — only the end-of-stream sentinel
            # died with the replica (see generate()).
            return {"done": []}
        trace = self._request_trace()
        if chaos.enabled():
            chaos.inject("serve_replica", phase="prefill",
                         tokens=len(prompt))
        q = self._queue_mod.Queue()
        with self._lock:
            rid = self.batcher.submit(prompt,
                                      max_new_tokens=max_tokens,
                                      trace=trace)
            self._queues[rid] = q
        self._work.set()
        tokens: List[int] = []
        try:
            while True:
                item = q.get(timeout=300)
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                tokens.append(item)
        finally:
            self._queues.pop(rid, None)
        with self._lock:
            if rid not in self.batcher.handoff_ready():
                # Finished entirely at prefill — a complete (short)
                # generation, not a handoff.
                return {"done": tokens}
            return kv_transfer.send_handoff(
                self.batcher, rid, deployment=self._req_deployment())

    def reserve_kv(self, prompt_len: int, max_new: int):
        """Pre-reserve decode arena blocks for an incoming handoff (the
        router calls this BEFORE dispatching prefill). Returns a
        replica-scoped ticket, or None when the arena cannot cover it
        (the import then allocates on arrival). Unspent tickets expire
        engine-side (``RAY_TPU_KV_RESERVE_TTL_S``)."""
        with self._lock:
            res = self.batcher.reserve_import(int(prompt_len),
                                              int(max_new))
        if res is None:
            return None
        return {"res_id": res, "nonce": self._nonce}

    def cancel_reserve(self, ticket) -> bool:
        if not isinstance(ticket, dict) or \
                ticket.get("nonce") != self._nonce:
            return False
        with self._lock:
            return self.batcher.cancel_reservation(ticket["res_id"])

    def decode_from(self, request: Dict[str, Any]):
        """Decode-role streaming entry: collect the journaled KV
        handoff named by ``request["manifest"]`` (shm channel read, crc
        verify, table-scatter into reserved blocks, radix insert) and
        stream EVERY token — the prefill-produced first token included.
        It reaches the caller only through this stream (the unary
        prefill response carries it solely inside the manifest), so the
        router's journal stays the single delivery ledger and greedy
        decode remains exactly-once across deaths.

        Chaos: ``kv_transfer``/``stage=import`` inside the transfer
        helper (decode death after the journaled handoff — the router
        replays as a fresh prefill, ``cause=resume``) and the usual
        ``serve_replica``/``phase=decode`` per-token site."""
        from ray_tpu._private import chaos
        from ray_tpu.serve import kv_transfer

        manifest = request["manifest"]
        ticket = request.get("reservation")
        res_id = None
        if isinstance(ticket, dict) and \
                ticket.get("nonce") == self._nonce:
            res_id = ticket.get("res_id")
        trace = self._request_trace()
        q = self._queue_mod.Queue()
        with self._lock:
            # The engine fires its first-token callback during the
            # import, before any queue could be registered under the
            # fresh rid — the manifest's first_token is delivered
            # explicitly below instead.
            rid = kv_transfer.receive_handoff(
                self.batcher, manifest, reservation=res_id,
                trace=trace, deployment=self._req_deployment())
            self._queues[rid] = q
        self._work.set()
        done = False
        emitted = 0
        try:
            if chaos.enabled():
                chaos.inject("serve_replica", phase="decode", token=0)
            emitted = 1
            yield int(manifest["first_token"])
            while True:
                token = q.get(timeout=300)
                if token is None:
                    done = True
                    return
                if isinstance(token, Exception):
                    done = True
                    raise token
                if chaos.enabled():
                    chaos.inject("serve_replica", phase="decode",
                                 token=emitted)
                emitted += 1
                yield token
        finally:
            self._queues.pop(rid, None)
            if not done:
                with self._lock:
                    self.batcher.cancel(rid)

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Non-streaming completion."""
        tokens = list(self.generate(request["prompt_token_ids"],
                                    request.get("max_tokens", 16)))
        return {"token_ids": tokens}


def build_continuous_llama_app(config: Optional[llama.LlamaConfig] = None,
                               num_replicas: int = 1, num_slots: int = 8,
                               max_len: int = 512, sync_every: int = 1,
                               use_decode_kernel: Optional[bool] = None,
                               paged: Optional[bool] = None,
                               block_size: int = 64,
                               kv_dtype: Optional[str] = None,
                               num_blocks: Optional[int] = None,
                               prefix_cache: Optional[bool] = None,
                               sampling=None,
                               spec_k: Optional[int] = None,
                               spec_draft_layers: Optional[int] = None,
                               spec_adaptive: Optional[bool] = None,
                               checkpoint_path: Optional[str] = None):
    dep = ContinuousLlamaDeployment.options(num_replicas=num_replicas)
    # Keyword bind so per-deploy ``init_kwargs`` overrides (serve config
    # files) can retarget any engine knob without positional conflicts.
    return dep.bind(config=config, num_slots=num_slots, max_len=max_len,
                    sync_every=sync_every,
                    use_decode_kernel=use_decode_kernel, paged=paged,
                    block_size=block_size, kv_dtype=kv_dtype,
                    num_blocks=num_blocks, prefix_cache=prefix_cache,
                    sampling=sampling, spec_k=spec_k,
                    spec_draft_layers=spec_draft_layers,
                    spec_adaptive=spec_adaptive,
                    checkpoint_path=checkpoint_path)


def build_disagg_llama_apps(name: str = "llm",
                            config: Optional[llama.LlamaConfig] = None,
                            num_prefill: int = 1, num_decode: int = 1,
                            **engine_kwargs):
    """(prefill_app, decode_app) Application pair for disaggregated
    serving, named ``<name>-prefill`` / ``<name>-decode``: the same
    engine knobs on both sides (geometry MUST match — the import
    rejects mismatched block_size/kv_dtype/model dims), the paged-KV
    plane forced on (roles require an arena to hand off). Deploy both
    and declare the role group, or use :func:`deploy_disagg_llama`
    which does all three."""
    engine_kwargs.setdefault("paged", True)
    prefill = ContinuousLlamaDeployment.options(
        name=f"{name}-prefill", num_replicas=num_prefill).bind(
        config=config, role="prefill", **engine_kwargs)
    decode = ContinuousLlamaDeployment.options(
        name=f"{name}-decode", num_replicas=num_decode).bind(
        config=config, role="decode", **engine_kwargs)
    return prefill, decode


def deploy_disagg_llama(name: str = "llm",
                        config: Optional[llama.LlamaConfig] = None,
                        num_prefill: int = 1, num_decode: int = 1,
                        **engine_kwargs) -> Dict[str, str]:
    """Deploy a disaggregated (prefill, decode) pair and register the
    role group under the logical ``name`` — streaming requests to
    ``/<name>/stream/...`` classify-and-split at the ingress from then
    on. Returns the group mapping."""
    prefill_app, decode_app = build_disagg_llama_apps(
        name=name, config=config, num_prefill=num_prefill,
        num_decode=num_decode, **engine_kwargs)
    serve.run(prefill_app, name=f"{name}-prefill")
    serve.run(decode_app, name=f"{name}-decode")
    serve.register_role_group(name, prefill=f"{name}-prefill",
                              decode=f"{name}-decode")
    return {"prefill": f"{name}-prefill", "decode": f"{name}-decode"}


__all__ += ["ContinuousLlamaDeployment", "build_continuous_llama_app",
            "build_disagg_llama_apps", "deploy_disagg_llama"]

from ray_tpu.llm.batch import LLMBatchWorker, batch_generate  # noqa: E402

__all__ += ["LLMBatchWorker", "batch_generate"]
