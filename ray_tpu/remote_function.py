"""RemoteFunction: the object returned by ``@ray_tpu.remote`` on a function.

Re-design of the reference (reference: ``python/ray/remote_function.py`` —
``RemoteFunction._remote`` :303): holds the user function plus default
options; ``.remote(*args)`` submits through the core runtime, ``.options()``
returns a shallow clone with overridden options.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

from ray_tpu._private import worker as _worker
from ray_tpu._private.options import (RemoteOptions, is_streaming,
                                      options_from_decorator_kwargs)


class RemoteFunction:
    def __init__(self, function, options: RemoteOptions):
        if not callable(function):
            raise TypeError("@remote must decorate a callable")
        self._function = function
        self._options = options
        self._function_name = getattr(function, "__qualname__",
                                      getattr(function, "__name__", "anonymous"))
        self._fn_ref = None  # lazily pickled-once form (hot-path cache)
        functools.update_wrapper(self, function)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function_name!r} cannot be called directly. "
            f"Use {self._function_name}.remote() instead.")

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def options(self, **option_overrides) -> "RemoteFunction":
        new = RemoteFunction.__new__(RemoteFunction)
        new._function = self._function
        new._function_name = self._function_name
        new._options = self._options.merged_with(option_overrides)
        new._fn_ref = self._fn_ref  # same function: share the pickled form
        functools.update_wrapper(new, self._function)
        return new

    def _remote(self, args, kwargs, options: RemoteOptions):
        # Pickle the function once per process, not once per task; workers
        # unpickle once per digest (fn_ref.py — the function-table analog).
        # Functions whose closure captures ObjectRefs are NOT cached
        # (FnRef.of returns None): each submit must re-serialize so the
        # contained refs get their flight-time pins.
        if self._fn_ref is None:
            from ray_tpu._private.fn_ref import FnRef

            try:
                self._fn_ref = FnRef.of(self._function) or self._function
            except Exception:  # noqa: BLE001 — unpicklable via FnRef path
                self._fn_ref = self._function
        refs = _worker.global_worker().core.submit_task(
            self._fn_ref, self._function_name, args, kwargs, options)
        if is_streaming(options.num_returns):
            # Generator task: refs[0] carries the final item count; items
            # stream out at deterministic ids (reference: ObjectRefStream).
            from ray_tpu._private.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0],
                                      owner_address=refs[0].owner_address())
        if options.num_returns == 1:
            return refs[0]
        return refs

    @property
    def func(self):
        """The underlying (non-remote) function."""
        return self._function

    def bind(self, *args, **kwargs):
        """Build a DAG node for compiled-graph execution (ray_tpu.dag)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)


def make_remote(function_or_class=None, **kwargs):
    """Implements ``@ray_tpu.remote`` / ``@ray_tpu.remote(**opts)``."""
    import inspect

    def decorator(target):
        if inspect.isclass(target):
            from ray_tpu.actor import ActorClass

            return ActorClass(target, options_from_decorator_kwargs(kwargs, True))
        return RemoteFunction(target, options_from_decorator_kwargs(kwargs, False))

    if function_or_class is not None:
        # Bare @remote with no arguments.
        if kwargs:
            raise TypeError("remote() takes either a function/class or options")
        return decorator(function_or_class)
    return decorator
