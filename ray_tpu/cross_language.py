"""Cross-language function registry + client gateway.

Reference: two reference components collapse into one mechanism here —
``ray.cross_language`` (calling functions across language workers by
descriptor) and the Ray Client server (``util/client/server/server.py:96``,
a proxy that runs driver operations on behalf of a remote thin client).

Python registers functions by name (exported through the GCS KV, like the
reference's function exports); any non-Python client connects to the
:class:`ClientGateway` over a framed-protobuf TCP socket and submits calls
by name with language-neutral ``XLangValue`` arguments. The gateway is a
real driver: it resolves the named function, submits it through the normal
task path, and translates results back — so the C++ API in ``cpp/`` gets
tasks, objects, and the KV without needing a gRPC or pickle stack.

Wire protocol (little-endian): request ``[u32 len][u8 op][protobuf]``,
reply ``[u32 len][u8 ok][protobuf]``. Ops: 1 KvPut, 2 KvGet, 3 Put,
4 Get, 5 Submit, 6 Wait, 7 Free (release a gateway-held ref),
8 CreateActor, 9 ActorCall, 10 KillActor.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Any, Dict, Optional

import cloudpickle

logger = logging.getLogger(__name__)

_KV_NS = "__xlang_fns__"

OP_KV_PUT = 1
OP_KV_GET = 2
OP_PUT = 3
OP_GET = 4
OP_SUBMIT = 5
OP_WAIT = 6
OP_FREE = 7
OP_CREATE_ACTOR = 8
OP_ACTOR_CALL = 9
OP_KILL_ACTOR = 10

# Backstop for clients that never Free: the gateway pins at most this many
# refs, evicting oldest-first (an evicted ref just loses its pin; the
# cluster refcount plane frees the object when no one else holds it).
MAX_HELD_REFS = 16384


def register_function(name: str, fn=None):
    """Export ``fn`` under ``name`` for cross-language callers
    (reference: function exports via GCS KV). Usable as a decorator."""
    from ray_tpu.experimental.internal_kv import internal_kv_put

    def do(f):
        internal_kv_put(name, cloudpickle.dumps(f), overwrite=True,
                        namespace=_KV_NS)
        return f

    return do if fn is None else do(fn)


_CPP_EXEC_NS = "__cpp_executors__"


def _call_cpp_executor(address: str, function: str, args,
                       op: int = 1) -> Any:
    """Dial a C++ TaskExecutor (cpp/include/ray_tpu/api.h) for one op:
    [u32 len][u8 op][XLangCall] -> [u32 len][u8 ok][XLangResult].
    op 1 = run a registered function; 2 = CreateActor (function = class
    name, returns the instance id); 3 = ActorCall (function =
    "<iid>:<method>"); 4 = KillActor (function = iid)."""
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    call = pb.XLangCall(function=function)
    for a in args:
        call.args.append(to_xlang_value(a))
    body = call.SerializeToString()
    host, port = address.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=30) as conn:
        conn.sendall(struct.pack("<IB", len(body), op) + body)
        header = ClientGateway._recv_exact(conn, 5)
        if header is None:
            raise ConnectionError(f"C++ executor at {address} hung up")
        (length,) = struct.unpack("<I", header[:4])
        reply = ClientGateway._recv_exact(conn, length)
        if reply is None:
            raise ConnectionError(f"C++ executor at {address} hung up")
    result = pb.XLangResult.FromString(reply)
    if not result.ok:
        raise RuntimeError(result.error or f"C++ op {function!r} failed")
    return from_xlang_value(result.value)


def _invoke_cpp(function: str, *args) -> Any:
    """Task body bridging to a C++ worker: resolve the executor address
    from the KV (re-read per call so a restarted C++ worker re-resolves)
    and forward the call. Runs inside a normal Python worker; the actual
    computation happens in the C++ process that registered ``function``."""
    from ray_tpu.experimental.internal_kv import internal_kv_get

    addr = internal_kv_get(function, namespace=_CPP_EXEC_NS)
    if addr is None:
        raise KeyError(f"no C++ executor registered for {function!r}")
    return _call_cpp_executor(addr.decode(), function, args)


def cpp_function(name: str):
    """Remote-callable handle to a C++-registered task (reference:
    ``ray.cross_language.cpp_function``). ``cpp_function("f").remote(x)``
    schedules a normal task whose body forwards to the C++ worker that
    registered ``f`` via ``TaskExecutor::Serve``."""
    import functools

    import ray_tpu

    return ray_tpu.remote(functools.partial(_invoke_cpp, name))


_CPP_ACTOR_NS = "__cpp_actor_classes__"


class _CppActorProxy:
    """Python proxy actor hosting ONE C++ actor instance (reference:
    C++ actors, ``cpp/src/ray/runtime/``): the instance lives in the C++
    process that registered the class via
    ``TaskExecutor::RegisterActorClass``; this proxy rides the normal
    actor machinery (placement, ordering, restarts, handle passing) and
    forwards each method call over the executor's framed socket."""

    def __init__(self, class_name: str, *ctor_args):
        from ray_tpu.experimental.internal_kv import internal_kv_get

        addr = internal_kv_get(class_name, namespace=_CPP_ACTOR_NS)
        if addr is None:
            raise KeyError(
                f"no C++ actor class registered as {class_name!r}")
        self._addr = addr.decode()
        self._iid = _call_cpp_executor(self._addr, class_name, ctor_args,
                                       op=2)

    def call(self, method: str, *args):
        return _call_cpp_executor(self._addr, f"{self._iid}:{method}",
                                  args, op=3)

    def release(self):
        """Free the C++-side instance (also called on proxy death)."""
        try:
            _call_cpp_executor(self._addr, self._iid, (), op=4)
        except Exception:  # noqa: BLE001 — executor already gone
            pass

    def __del__(self):
        self.release()


class _GatewayCppActor:
    """Gateway-held adapter for a C++-defined actor: translates
    ActorCall frames into the proxy's ``call`` method."""

    def __init__(self, proxy_handle):
        self.handle = proxy_handle
        self._actor_id = proxy_handle._actor_id

    def call_method(self, method: str, args):
        return self.handle.call.remote(method, *args)


_PROXY_REMOTE_CLS = None


def _proxy_cls():
    global _PROXY_REMOTE_CLS
    if _PROXY_REMOTE_CLS is None:
        import ray_tpu

        _PROXY_REMOTE_CLS = ray_tpu.remote(_CppActorProxy)
    return _PROXY_REMOTE_CLS


class _CppActorMethod:
    def __init__(self, handle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args):
        return self._handle._proxy.call.remote(self._method, *args)


class CppActorHandle:
    """Handle to a C++-defined actor: attribute access yields remote
    methods, exactly like a Python ActorHandle."""

    def __init__(self, proxy_handle):
        self._proxy = proxy_handle

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _CppActorMethod(self, name)

    def kill(self, no_restart: bool = True):
        import ray_tpu

        try:
            # Best-effort: a crashed proxy can't release, but the kill
            # below must still clean it up without raising.
            ray_tpu.get(self._proxy.release.remote(), timeout=30)
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.kill(self._proxy, no_restart=no_restart)


class _CppActorClass:
    def __init__(self, name: str):
        self._name = name

    def remote(self, *ctor_args) -> CppActorHandle:
        # Creation is async, like any actor: an unknown class or a ctor
        # raise surfaces on the first method call (normal actor
        # semantics).
        return CppActorHandle(_proxy_cls().remote(self._name, *ctor_args))


def cpp_actor_class(name: str) -> _CppActorClass:
    """Handle to a C++-registered actor CLASS:
    ``cpp_actor_class("Counter").remote(args)`` creates the instance in
    the C++ worker that registered it; the returned handle's methods
    forward through a Python proxy actor (reference:
    ``ray.cross_language.cpp_actor_class``)."""
    return _CppActorClass(name)


def _resource_opts(resources) -> Dict[str, Any]:
    """XLangCall.resources -> remote() options (shared by task submit and
    actor creation)."""
    opts: Dict[str, Any] = {}
    res = dict(resources)
    if "CPU" in res:
        opts["num_cpus"] = res.pop("CPU")
    if "TPU" in res:
        opts["num_tpus"] = res.pop("TPU")
    if res:
        opts["resources"] = res
    return opts


def to_xlang_value(v) -> "Any":
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    out = pb.XLangValue()
    if isinstance(v, bool):
        out.flag = v
    elif isinstance(v, int):
        out.i = v
    elif isinstance(v, float):
        out.d = v
    elif isinstance(v, str):
        out.s = v
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.b = bytes(v)
    else:
        raise TypeError(
            f"value of type {type(v).__name__} is not cross-language "
            "portable (use float/int/str/bytes/bool)")
    return out


def from_xlang_value(x) -> Any:
    kind = x.WhichOneof("kind")
    if kind is None:
        return None
    return getattr(x, kind)


class ClientGateway:
    """Framed-protobuf TCP server proxying a driver for thin clients."""

    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=gcs_address, ignore_reinit_error=True)
        self._ray = ray_tpu
        self._fns: Dict[str, Any] = {}   # name -> (kv blob, remote function)
        self._actor_classes: Dict[str, Any] = {}  # name -> (blob, ActorClass)
        # actor id -> handle, held for the client's lifetime (killed via
        # OP_KILL_ACTOR). Bounded: evicted handles are KILLED — unlike an
        # evicted ref (which only loses its pin), a dropped ActorHandle
        # has no GC and would leak the running actor forever.
        self._actors: Dict[bytes, Any] = {}
        # object id -> ObjectRef, insertion-ordered for MAX_HELD_REFS
        # eviction; clients release explicitly with OP_FREE.
        self._refs: Dict[bytes, Any] = {}
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="xlang-gateway")
        self._thread.start()

    # ------------------------------------------------------------ serving
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                header = self._recv_exact(conn, 5)
                if header is None:
                    return
                (length,), op = struct.unpack("<I", header[:4]), header[4]
                body = self._recv_exact(conn, length)
                if body is None:
                    return
                try:
                    ok, reply = self._dispatch(op, body)
                except Exception as e:  # noqa: BLE001
                    ok, reply = False, str(e).encode()
                conn.sendall(struct.pack("<IB", len(reply), 1 if ok else 0)
                             + reply)
        except Exception:  # noqa: BLE001 — client went away
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv_exact(conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # ------------------------------------------------------------ dispatch
    def _dispatch(self, op: int, body: bytes):
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        ray_tpu = self._ray
        if op == OP_KV_PUT:
            from ray_tpu.experimental.internal_kv import internal_kv_put

            req = pb.KvRequest.FromString(body)
            ok = internal_kv_put(req.key, bytes(req.value), overwrite=True,
                                 namespace=req.ns or "default")
            return True, pb.KvReply(ok=bool(ok)).SerializeToString()
        if op == OP_KV_GET:
            from ray_tpu.experimental.internal_kv import internal_kv_get

            req = pb.KvRequest.FromString(body)
            val = internal_kv_get(req.key, namespace=req.ns or "default")
            if val is None:
                return True, pb.KvReply(found=False).SerializeToString()
            return True, pb.KvReply(found=True,
                                    value=val).SerializeToString()
        if op == OP_PUT:
            val = from_xlang_value(pb.XLangValue.FromString(body))
            ref = ray_tpu.put(val)
            self._hold(ref)
            return True, pb.GatewayRef(
                object_id=ref.id().binary()).SerializeToString()
        if op == OP_GET:
            ref_msg = pb.GatewayRef.FromString(body)
            with self._lock:
                ref = self._refs.get(bytes(ref_msg.object_id))
            if ref is None:
                return True, pb.XLangResult(
                    ok=False,
                    error="unknown object id (gateway-held refs only)"
                ).SerializeToString()
            try:
                value = ray_tpu.get(ref, timeout=120)
                return True, pb.XLangResult(
                    ok=True,
                    value=to_xlang_value(value)).SerializeToString()
            except Exception as e:  # noqa: BLE001
                return True, pb.XLangResult(
                    ok=False, error=str(e)).SerializeToString()
        if op == OP_SUBMIT:
            call = pb.XLangCall.FromString(body)
            fn = self._resolve(call.function)
            args = [from_xlang_value(a) for a in call.args]
            opts = _resource_opts(call.resources)
            remote = fn.options(**opts) if opts else fn
            ref = remote.remote(*args)
            self._hold(ref)
            return True, pb.GatewayRef(
                object_id=ref.id().binary()).SerializeToString()
        if op == OP_WAIT:
            ref_msg = pb.GatewayRef.FromString(body)
            with self._lock:
                ref = self._refs.get(bytes(ref_msg.object_id))
            ready = []
            if ref is not None:
                ready, _ = ray_tpu.wait([ref], timeout=0)
            return True, pb.XLangResult(
                ok=bool(ready)).SerializeToString()
        if op == OP_FREE:
            ref_msg = pb.GatewayRef.FromString(body)
            with self._lock:
                found = self._refs.pop(bytes(ref_msg.object_id),
                                       None) is not None
            return True, pb.XLangResult(ok=found).SerializeToString()
        # Actor ops (reference: the Ray Client proxies actor lifecycle +
        # method calls for thin clients, util/client/server/server.py:96).
        if op == OP_CREATE_ACTOR:
            call = pb.XLangCall.FromString(body)
            args = [from_xlang_value(a) for a in call.args]
            opts = _resource_opts(call.resources)
            try:
                actor_cls = self._resolve_actor_class(call.function)
            except KeyError:
                # Not a Python class: a C++ TaskExecutor may have
                # registered it (RegisterActorClass) — create through the
                # proxy actor so C++ clients drive C++-defined actors.
                from ray_tpu.experimental.internal_kv import internal_kv_get

                if internal_kv_get(call.function,
                                   namespace=_CPP_ACTOR_NS) is None:
                    raise
                proxy_cls = _proxy_cls()
                if opts:
                    proxy_cls = proxy_cls.options(**opts)
                handle = _GatewayCppActor(
                    proxy_cls.remote(call.function, *args))
            else:
                remote_cls = actor_cls.options(**opts) if opts \
                    else actor_cls
                handle = remote_cls.remote(*args)
            aid = handle._actor_id.binary()
            evicted = []
            with self._lock:
                self._actors[aid] = handle
                while len(self._actors) > MAX_HELD_REFS:
                    evicted.append(self._actors.pop(
                        next(iter(self._actors))))
            for old in evicted:
                # Unlike an evicted ref (which only loses its pin), a
                # dropped ActorHandle has no GC: kill or it leaks forever.
                try:
                    if isinstance(old, _GatewayCppActor):
                        # Free the C++-side instance or it leaks in the
                        # executor's map for its whole lifetime.
                        try:
                            ray_tpu.get(old.handle.release.remote(),
                                        timeout=30)
                        except Exception:  # noqa: BLE001
                            pass
                        old = old.handle
                    ray_tpu.kill(old)
                except Exception:  # noqa: BLE001
                    pass
            return True, pb.GatewayRef(object_id=aid).SerializeToString()
        if op == OP_ACTOR_CALL:
            call = pb.XLangActorCall.FromString(body)
            with self._lock:
                handle = self._actors.get(bytes(call.actor_id))
            if handle is None:
                # ok=0 frame, like every other op's errors: the C++
                # client parses a success frame as GatewayRef and would
                # silently swallow an inline XLangResult error.
                raise KeyError(
                    "unknown actor id (gateway-held actors only)")
            args = [from_xlang_value(a) for a in call.args]
            if isinstance(handle, _GatewayCppActor):
                ref = handle.call_method(call.method, args)
            else:
                ref = getattr(handle, call.method).remote(*args)
            self._hold(ref)
            return True, pb.GatewayRef(
                object_id=ref.id().binary()).SerializeToString()
        if op == OP_KILL_ACTOR:
            ref_msg = pb.GatewayRef.FromString(body)
            with self._lock:
                handle = self._actors.pop(bytes(ref_msg.object_id), None)
            if handle is not None:
                if isinstance(handle, _GatewayCppActor):
                    # Free the C++-side instance before the proxy dies.
                    try:
                        ray_tpu.get(handle.handle.release.remote(),
                                    timeout=30)
                    except Exception:  # noqa: BLE001
                        pass
                    handle = handle.handle
                ray_tpu.kill(handle)
            return True, pb.XLangResult(
                ok=handle is not None).SerializeToString()
        raise ValueError(f"unknown gateway op {op}")

    def _resolve_actor_class(self, name: str):
        """A registered class exported for cross-language actor creation
        (register_function accepts classes too)."""
        import ray_tpu
        from ray_tpu.experimental.internal_kv import internal_kv_get

        blob = internal_kv_get(name, namespace=_KV_NS)
        if blob is None:
            raise KeyError(f"no cross-language class registered as "
                           f"{name!r}")
        with self._lock:
            cached = self._actor_classes.get(name)
            if cached is not None and cached[0] == blob:
                return cached[1]
        cls = cloudpickle.loads(blob)
        if not isinstance(cls, type):
            raise TypeError(f"{name!r} is registered as a function, not a "
                            f"class; use Submit for functions")
        actor_cls = ray_tpu.remote(cls)
        with self._lock:
            self._actor_classes[name] = (blob, actor_cls)
        return actor_cls

    def _hold(self, ref) -> None:
        with self._lock:
            self._refs[ref.id().binary()] = ref
            while len(self._refs) > MAX_HELD_REFS:
                self._refs.pop(next(iter(self._refs)))

    def _resolve(self, name: str):
        import ray_tpu
        from ray_tpu.experimental.internal_kv import internal_kv_get

        # The KV is re-read every call (one cheap RPC) so re-registering a
        # name takes effect immediately; the unpickle + remote-wrap is
        # cached keyed on the blob bytes.
        blob = internal_kv_get(name, namespace=_KV_NS)
        if blob is None:
            # Not a Python-registered function: a C++ TaskExecutor may own
            # the name — route the call to it (C++ client -> gateway ->
            # C++ worker completes the cross-language loop). Cached like
            # the Python path, keyed on the executor's address.
            addr = internal_kv_get(name, namespace=_CPP_EXEC_NS)
            if addr is not None:
                key = b"cpp:" + addr
                with self._lock:
                    cached = self._fns.get(name)
                    if cached is not None and cached[0] == key:
                        return cached[1]
                fn = cpp_function(name)
                with self._lock:
                    self._fns[name] = (key, fn)
                return fn
            raise KeyError(f"no cross-language function registered as "
                           f"{name!r}")
        with self._lock:
            cached = self._fns.get(name)
            if cached is not None and cached[0] == blob:
                return cached[1]
        target = cloudpickle.loads(blob)
        if isinstance(target, type):
            # Mirror of _resolve_actor_class's guard: Submit on a class
            # would instantiate an actor and then crash holding its
            # result ref — leaking a running actor nothing tracks.
            raise TypeError(f"{name!r} is registered as a class; use "
                            f"CreateActor for classes")
        fn = ray_tpu.remote(target)
        with self._lock:
            self._fns[name] = (blob, fn)
        return fn

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
