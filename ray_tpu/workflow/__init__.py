"""ray_tpu.workflow: durable workflow execution.

Reference: ``python/ray/workflow`` (SURVEY.md §2.4) — DAGs whose step results
are checkpointed to storage, so a crashed/resumed run re-executes only the
steps without a persisted result. Steps are the same ``.bind()`` DAG nodes as
:mod:`ray_tpu.dag`; ``workflow.run`` walks the graph, consults the on-disk
result store keyed by (workflow_id, step hash), executes missing steps as
remote tasks, and records results durably before proceeding.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode

_storage_root: Optional[str] = None


def init(storage: Optional[str] = None) -> None:
    global _storage_root
    _storage_root = storage or os.path.join(tempfile.gettempdir(),
                                            "ray_tpu_workflows")
    os.makedirs(_storage_root, exist_ok=True)


def _storage() -> str:
    if _storage_root is None:
        init()
    return _storage_root  # type: ignore[return-value]


def _step_key(node: DAGNode, resolved_args, resolved_kwargs) -> str:
    """Content-address a step by function name + argument repr."""
    fn_name = getattr(getattr(node, "_remote_fn", None), "_function_name",
                      type(node).__name__)
    payload = repr((fn_name, resolved_args, sorted(resolved_kwargs.items())))
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


class _WorkflowRunner:
    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(_storage(), workflow_id)
        os.makedirs(self.dir, exist_ok=True)

    def _result_path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.pkl")

    def has(self, key: str) -> bool:
        return os.path.exists(self._result_path(key))

    def load(self, key: str) -> Any:
        with open(self._result_path(key), "rb") as f:
            return pickle.load(f)

    def save(self, key: str, value: Any) -> None:
        tmp = self._result_path(key) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self._result_path(key))  # atomic commit

    def run_node(self, node, cache: Dict[int, Any]) -> Any:
        if not isinstance(node, DAGNode):
            return node
        if id(node) in cache:
            return cache[id(node)]
        args = tuple(self.run_node(a, cache) for a in node._bound_args)
        kwargs = {k: self.run_node(v, cache)
                  for k, v in node._bound_kwargs.items()}
        if isinstance(node, InputNode):
            raise ValueError("workflow DAGs take inputs via bind()")
        if isinstance(node, FunctionNode):
            key = _step_key(node, args, kwargs)
            if self.has(key):
                value = self.load(key)
            else:
                value = ray_tpu.get(node._remote_fn.remote(*args, **kwargs))
                self.save(key, value)
                hook = getattr(node, "_post_commit", None)
                if hook is not None:
                    hook()
        else:
            raise TypeError(
                f"workflow steps must be task nodes, got {type(node).__name__}")
        cache[id(node)] = value
        return value


# ------------------------------------------------------------- events
class EventListener:
    """External-event hook for durable workflows (reference:
    ``workflow/event_listener.py``): subclass and implement
    :meth:`poll_for_event`; the returned payload becomes the step's
    checkpointed result, so a resumed workflow does NOT re-wait for an
    event it already received."""

    def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError


class KVEventListener(EventListener):
    """Default listener: waits for ``send_event(key, payload)`` via the
    cluster KV (cross-process, works in both runtimes)."""

    POLL_PERIOD_S = 0.2
    EVENT_NS = "__wf_events__"

    def poll_for_event(self, key: str,
                       timeout: Optional[float] = None) -> Any:
        import time

        from ray_tpu.experimental.internal_kv import internal_kv_get

        deadline = None if timeout is None else \
            time.monotonic() + timeout
        while True:
            blob = internal_kv_get(key, namespace=self.EVENT_NS)
            if blob is not None:
                # NOT deleted here: consumption commits only after the
                # step result persists (the post-commit hook in
                # wait_for_event), so a crash between receipt and
                # checkpoint can't lose the event.
                return pickle.loads(blob)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"workflow event {key!r} not received in {timeout}s")
            time.sleep(self.POLL_PERIOD_S)


def send_event(key: str, payload: Any = None) -> None:
    """Publish an event. Single-consumer semantics: the first waiting
    step to checkpoint the payload consumes the key (post-commit), so a
    reused key is never satisfied by a stale event."""
    from ray_tpu.experimental.internal_kv import internal_kv_put

    internal_kv_put(key, pickle.dumps(payload),
                    namespace=KVEventListener.EVENT_NS)


def wait_for_event(*args, listener_cls=KVEventListener,
                   **kwargs) -> DAGNode:
    """A DAG step that blocks until the listener observes its event and
    checkpoints the payload (reference: ``workflow.wait_for_event``).
    Resume semantics come for free: a received event is a persisted step
    result, so re-running the workflow never re-waits.

    Step identity is content-addressed from the listener class + args —
    pass plain values (strings/numbers), not live objects.
    """
    node = _wait_for_event_step.bind(listener_cls, args, kwargs)
    if listener_cls is KVEventListener and args:
        key = args[0]

        def _consume():
            from ray_tpu.experimental.internal_kv import internal_kv_del

            internal_kv_del(key, namespace=KVEventListener.EVENT_NS)

        # Runs AFTER the step result is durably checkpointed — exactly-
        # once consumption without a lost-event crash window.
        node._post_commit = _consume
    return node


@ray_tpu.remote
def _wait_for_event_step(listener_cls, args, kwargs):
    return listener_cls().poll_for_event(*args, **kwargs)


def run(dag: DAGNode, *, workflow_id: str) -> Any:
    """Run (or resume) a workflow; completed steps are skipped on resume."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    runner = _WorkflowRunner(workflow_id)
    result = runner.run_node(dag, {})
    runner.save("__result__", result)
    return result


def get_output(workflow_id: str) -> Any:
    runner = _WorkflowRunner(workflow_id)
    if not runner.has("__result__"):
        raise ValueError(f"workflow {workflow_id!r} has no recorded output")
    return runner.load("__result__")


def list_all():
    root = _storage()
    return [d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))]


def delete(workflow_id: str):
    import shutil

    shutil.rmtree(os.path.join(_storage(), workflow_id), ignore_errors=True)


__all__ = ["EventListener", "KVEventListener", "delete", "get_output",
           "init", "list_all", "run", "send_event", "wait_for_event"]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("workflow")
del _rlu
