"""Chip pool arbiter: crash-safe serve<->train chip handoffs.

TPU chips are the scarce resource and two live workloads share them: the
serve fleet (replicas, each owning ``chips_per_replica``) and the elastic
trainer (workers, each owning ``chips_per_worker``). This module closes
the diurnal loop — serve sheds replicas at night, training grows its mesh
to absorb the freed chips, and the handoff reverses under morning load —
with the handoff itself surviving preemption, replica death, and arbiter
crash mid-flight.

Reference shape: the v2 autoscaler's instance manager
(``python/ray/autoscaler/v2/instance_manager``) — an explicit status
machine with validated transitions and recorded history — applied to chip
*leases* instead of cloud instances, with the GCS KV (namespace
``__pool__``, WAL-durable in cluster mode) as the journal.

Ledger model
============

The pool has a fixed ``total`` of chips and a journaled ``base`` split
(``config`` key). Every movement is a **lease**: a journaled record that
walks an explicit state machine::

    PENDING -> FREEING -> FREED -> GRANTING -> COMMITTED
                  |          |        |
                  +----------+--------+--> ABORTING -> ABORTED
    COMMITTED -> RETURN_FREEING -> RETURN_GRANTING -> RETURNED

* ``FREEING``: the donor is releasing chips (serve: controller-driven
  graceful drain of victim replicas through the PR-13 drain path; train:
  a ``world_target`` shrink ask over the preempt pubsub channel).
* ``FREED``: the donor confirmed the chips are free.
* ``GRANTING``: the recipient is absorbing (train: grow ``world_target``
  published to the trainer's ResizeGuard; serve: replicas spawned via the
  deployment's ``checkpoint_path`` cold-start).
* ``COMMITTED``: the recipient confirmed (mesh re-formed at the leased
  world / replicas routed); the lease is live and carries a **deadline**
  — expiry automatically returns the chips to the donor.
* ``RETURN_*``: the reverse handoff (deadline expiry or SLO reversal).
* ``ABORTING``/``ABORTED``: rollback before commit — chips go back to
  the donor.

**Chip conservation is structural**: each lease contributes a pure
per-stage delta to the derived allocation (transitional stages hold the
chips ``in_flight``; COMMITTED credits the recipient; terminal stages net
zero), so ``serve + train + in_flight == total`` on every tick by
construction, and :meth:`PoolLedger.verify` asserts it plus
non-negativity — a violation means a journal bug, not a race.

**Crash safety**: every transition goes through ONE journaled helper
(:meth:`PoolLedger._journal_put` — a tier-1 source lint pins this); a
restarted arbiter reloads the journal, re-issues the recorded absolute
targets for the stage each lease was parked in (the side effects —
``pool_set_replicas``, ``request_resize`` — are idempotent), and resumes
or rolls back. Stages that stop converging past
``RAY_TPU_POOL_STAGE_TIMEOUT_S`` roll back rather than wedge.

**SLO guard**: while the serve plane's shed rate or TTFT/latency p95
regress, the arbiter refuses to take serve chips (PENDING serve-donor
leases abort) and reverses the newest committed serve->train lease; the
reversal is journaled under ``last_reversal`` for the CLI/dashboard.

Chaos sites (``_private/chaos.py``): ``pool_tick`` (``kill_arbiter``)
fires at the top of :meth:`ChipPoolArbiter.tick`; ``pool_handoff``
(``preempt_node``) fires before every lease advance, matchable on
``stage=``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import events as _events

logger = logging.getLogger(__name__)

POOL_KV_NS = "__pool__"

# Lease stages.
PENDING = "PENDING"                  # journaled intent, nothing moved yet
FREEING = "FREEING"                  # donor releasing (drain / shrink ask)
FREED = "FREED"                      # donor confirmed chips free
GRANTING = "GRANTING"                # recipient absorbing (grow / spawn)
COMMITTED = "COMMITTED"              # recipient confirmed; deadline armed
RETURN_FREEING = "RETURN_FREEING"    # recipient giving the chips back
RETURN_GRANTING = "RETURN_GRANTING"  # donor re-absorbing
RETURNED = "RETURNED"                # terminal: chips back at the donor
ABORTING = "ABORTING"                # rollback before commit
ABORTED = "ABORTED"                  # terminal: rollback complete

_LEASE_TRANSITIONS = {
    PENDING: {FREEING, ABORTING, ABORTED},
    FREEING: {FREED, ABORTING},
    FREED: {GRANTING, ABORTING},
    GRANTING: {COMMITTED, ABORTING},
    COMMITTED: {RETURN_FREEING},
    RETURN_FREEING: {RETURN_GRANTING},
    RETURN_GRANTING: {RETURNED},
    ABORTING: {ABORTED},
    RETURNED: set(),
    ABORTED: set(),
}

TERMINAL = frozenset({RETURNED, ABORTED})
TRANSITIONAL = frozenset({FREEING, FREED, GRANTING, ABORTING,
                          RETURN_FREEING, RETURN_GRANTING})


class InvalidLeaseTransition(RuntimeError):
    pass


def _stage_delta(stage: str, chips: int) -> Tuple[int, int, int]:
    """(d_donor, d_recipient, d_in_flight) a lease contributes to the
    derived allocation — a pure function of its stage, so the ledger's
    chip accounting is replayable from the journal alone."""
    if stage in TRANSITIONAL:
        return -chips, 0, chips
    if stage == COMMITTED:
        return -chips, chips, 0
    return 0, 0, 0  # PENDING / RETURNED / ABORTED


def compute_allocation(config: Dict[str, Any],
                       leases: List[Dict[str, Any]]) -> Dict[str, int]:
    """Derived chip allocation: base split + every lease's stage delta.
    Shared by the live arbiter, the CLI, and the dashboard so all three
    views agree by construction."""
    alloc = {"serve": int(config["base"]["serve"]),
             "train": int(config["base"]["train"]), "in_flight": 0}
    for lease in leases:
        d_donor, d_recip, d_infl = _stage_delta(lease["stage"],
                                                int(lease["chips"]))
        alloc[lease["donor"]] += d_donor
        alloc[lease["recipient"]] += d_recip
        alloc["in_flight"] += d_infl
    alloc["total"] = int(config["total"])
    return alloc


# --------------------------------------------------------------- KV stores

class DictKv:
    """In-memory KV with the journal surface — unit tests replay
    truncated journals through it without a runtime."""

    def __init__(self, data: Optional[Dict[str, bytes]] = None):
        self.data: Dict[str, bytes] = dict(data or {})

    def get(self, key: str) -> Optional[bytes]:
        return self.data.get(key)

    def put(self, key: str, value: bytes) -> None:
        self.data[key] = bytes(value)

    def delete(self, key: str) -> None:
        self.data.pop(key, None)

    def keys(self, prefix: str = "") -> List[str]:
        return [k for k in self.data if k.startswith(prefix)]


class InternalKv:
    """The production store: GCS KV namespace ``__pool__`` (WAL-durable
    in cluster mode; the in-process runtime's KV dict locally)."""

    def __init__(self, namespace: str = POOL_KV_NS):
        self.namespace = namespace

    def get(self, key: str) -> Optional[bytes]:
        from ray_tpu.experimental import internal_kv as kv

        return kv.internal_kv_get(key, namespace=self.namespace)

    def put(self, key: str, value: bytes) -> None:
        from ray_tpu.experimental import internal_kv as kv

        kv.internal_kv_put(key, value, overwrite=True,
                           namespace=self.namespace)

    def delete(self, key: str) -> None:
        from ray_tpu.experimental import internal_kv as kv

        kv.internal_kv_del(key, namespace=self.namespace)

    def keys(self, prefix: str = "") -> List[str]:
        from ray_tpu.experimental import internal_kv as kv

        return kv.internal_kv_list(prefix, namespace=self.namespace)


class GrpcKv:
    """The ``__pool__`` namespace over loopback gRPC: a PoolLedger
    journaling straight against a GcsServer's Kv handlers, no runtime
    required. This is how bench_control.py's arbiter ticks exercise the
    REAL head KV/WAL path (and how an out-of-process arbiter would)."""

    def __init__(self, address: str, namespace: str = POOL_KV_NS):
        from ray_tpu._private import rpc

        self.namespace = namespace
        self._stub = rpc.get_stub("GcsService", address)

    def get(self, key: str) -> Optional[bytes]:
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        reply = self._stub.KvGet(pb.KvRequest(ns=self.namespace, key=key))
        return bytes(reply.value) if reply.found else None

    def put(self, key: str, value: bytes) -> None:
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        self._stub.KvPut(pb.KvRequest(ns=self.namespace, key=key,
                                      value=bytes(value), overwrite=True))

    def delete(self, key: str) -> None:
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        self._stub.KvDel(pb.KvRequest(ns=self.namespace, key=key))

    def keys(self, prefix: str = "") -> List[str]:
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        reply = self._stub.KvKeys(pb.KvRequest(ns=self.namespace,
                                               prefix=prefix))
        return list(reply.keys)


# ----------------------------------------------------------------- ledger

class PoolLedger:
    """Journaled lease table over a KV store.

    Every write goes through :meth:`_journal_put` / :meth:`_journal_del`
    — the single chokepoints a tier-1 source lint pins, so no transition
    can bypass the journal.
    """

    MAX_TERMINAL_KEPT = 256
    MAX_HISTORY = 64

    def __init__(self, kv=None):
        self.kv = kv if kv is not None else InternalKv()

    # ----------------------------------------------------- journal I/O
    def _journal_put(self, key: str, record: Dict[str, Any]) -> None:
        """THE ledger write: one key, one JSON record, via the KV store
        (GCS KV -> WAL in cluster mode). Every config/lease/reversal
        mutation funnels here."""
        self.kv.put(key, json.dumps(record, sort_keys=True).encode())

    def _journal_del(self, key: str) -> None:
        """THE ledger delete (terminal-lease pruning only)."""
        self.kv.delete(key)

    def _read(self, key: str) -> Optional[Dict[str, Any]]:
        raw = self.kv.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except Exception:  # noqa: BLE001 — a torn record is a violation
            logger.error("pool ledger: unreadable record %r", key)
            return None

    # ----------------------------------------------------------- state
    def config(self) -> Optional[Dict[str, Any]]:
        return self._read("config")

    def bootstrap(self, serve_chips: int, train_chips: int,
                  meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Journal the pool's base split once; an existing config wins
        (a restarted arbiter must not re-baseline over live leases)."""
        cfg = self.config()
        if cfg is not None:
            return cfg
        cfg = {"total": int(serve_chips) + int(train_chips),
               "base": {"serve": int(serve_chips),
                        "train": int(train_chips)},
               "ts": time.time(), **(meta or {})}
        self._journal_put("config", cfg)
        return cfg

    def leases(self, stages: Optional[frozenset] = None
               ) -> List[Dict[str, Any]]:
        out = []
        for key in sorted(self.kv.keys("lease/")):
            rec = self._read(key)
            if rec is None:
                continue
            if stages is None or rec["stage"] in stages:
                out.append(rec)
        return out

    def get_lease(self, lease_id: str) -> Optional[Dict[str, Any]]:
        return self._read(f"lease/{lease_id}")

    def create_lease(self, donor: str, recipient: str, chips: int,
                     lease_s: float) -> Dict[str, Any]:
        if donor == recipient or {donor, recipient} - {"serve", "train"}:
            raise ValueError(f"bad handoff {donor}->{recipient}")
        if chips <= 0:
            raise ValueError(f"bad chip count {chips}")
        lease = {
            "lease_id": f"lease-{uuid.uuid4().hex[:12]}",
            "donor": donor, "recipient": recipient, "chips": int(chips),
            "stage": PENDING, "created_ts": time.time(),
            "lease_s": float(lease_s), "deadline_ts": None,
            "history": [[PENDING, time.time(), "created"]],
        }
        self._journal_put(f"lease/{lease['lease_id']}", lease)
        _events.emit("pool.lease", subject={"lease_id": lease["lease_id"]},
                     stage=PENDING, detail="created", donor=donor,
                     recipient=recipient, chips=int(chips))
        return lease

    def advance(self, lease: Dict[str, Any], stage: str,
                detail: str = "", cause_event: str = "",
                **fields: Any) -> Dict[str, Any]:
        """Validated, journaled transition (+ optional recorded fields,
        e.g. the absolute targets a restarted arbiter re-issues).
        ``cause_event`` links the flight-recorder record for this
        transition to the event that forced it (SLO breach, preemption
        notice)."""
        if stage not in _LEASE_TRANSITIONS.get(lease["stage"], set()):
            raise InvalidLeaseTransition(
                f"lease {lease['lease_id']}: {lease['stage']} -> {stage}")
        lease = dict(lease, stage=stage, **fields)
        hist = list(lease["history"])[-self.MAX_HISTORY + 1:]
        hist.append([stage, time.time(), detail])
        lease["history"] = hist
        self._journal_put(f"lease/{lease['lease_id']}", lease)
        _events.emit("pool.lease", cause=cause_event,
                     subject={"lease_id": lease["lease_id"]},
                     stage=stage, detail=detail, donor=lease["donor"],
                     recipient=lease["recipient"], chips=lease["chips"])
        if stage in TERMINAL:
            self._prune()
        return lease

    def record_reversal(self, lease: Dict[str, Any], action: str,
                        signal: str, detail: str = "",
                        cause_event: str = "") -> str:
        self._journal_put("last_reversal", {
            "lease_id": lease["lease_id"], "action": action,
            "signal": signal, "detail": detail, "ts": time.time(),
            "chips": lease["chips"],
            "direction": f"{lease['donor']}_to_{lease['recipient']}"})
        return _events.emit(
            "pool.reversal", cause=cause_event,
            subject={"lease_id": lease["lease_id"]},
            action=action, signal=signal, detail=detail)

    def last_reversal(self) -> Optional[Dict[str, Any]]:
        return self._read("last_reversal")

    def _prune(self) -> None:
        terminal = [rec for rec in self.leases(TERMINAL)]
        excess = len(terminal) - self.MAX_TERMINAL_KEPT
        if excess > 0:
            terminal.sort(key=lambda r: r["history"][-1][1])
            for rec in terminal[:excess]:
                self._journal_del(f"lease/{rec['lease_id']}")

    # ------------------------------------------------------- invariants
    def allocation(self, leases: Optional[List[Dict[str, Any]]] = None
                   ) -> Dict[str, int]:
        """Derived allocation; pass an already-read ``leases`` snapshot
        to avoid re-scanning the journal (tick() reads it once and
        shares it with verify/gauges — each scan is a KvKeys plus a
        KvGet per lease against the GCS in cluster mode)."""
        cfg = self.config()
        if cfg is None:
            return {"serve": 0, "train": 0, "in_flight": 0, "total": 0}
        return compute_allocation(
            cfg, self.leases() if leases is None else leases)

    def verify(self, leases: Optional[List[Dict[str, Any]]] = None
               ) -> List[str]:
        """The chip conservation invariant: every chip in exactly one
        ledger state, none leased to two owners, none orphaned. Returns
        human-readable violations (empty = healthy)."""
        cfg = self.config()
        if cfg is None:
            return []
        if leases is None:
            leases = self.leases()
        violations = []
        alloc = compute_allocation(cfg, leases)
        for owner in ("serve", "train", "in_flight"):
            if alloc[owner] < 0:
                violations.append(
                    f"negative_share: {owner}={alloc[owner]} "
                    f"(a chip is leased to two owners)")
        booked = alloc["serve"] + alloc["train"] + alloc["in_flight"]
        if booked != alloc["total"]:
            violations.append(
                f"total_mismatch: serve+train+in_flight={booked} != "
                f"total={alloc['total']} (orphaned chips)")
        for lease in leases:
            if lease["chips"] <= 0:
                violations.append(
                    f"empty_lease: {lease['lease_id']}")
            if lease["stage"] not in _LEASE_TRANSITIONS:
                violations.append(
                    f"unknown_stage: {lease['lease_id']} "
                    f"{lease['stage']}")
        return violations


# ------------------------------------------------------ workload adapters

class ServeWorkload:
    """The serve fleet's side of a handoff, over the serve controller's
    pool surface (``pool_set_replicas`` / ``pool_state``): shrink =
    graceful drain of victims, grow = replica spawn (checkpoint
    cold-start when the deployment was built with ``checkpoint_path``),
    and a chip cap that stops the pressure autoscaler re-growing into
    leased-away chips."""

    kind = "serve"

    def __init__(self, deployment: str, chips_per_replica: int = 1,
                 min_chips: Optional[int] = None):
        self.deployment = deployment
        self.cpr = max(int(chips_per_replica), 1)
        self.min_chips = (int(min_chips) if min_chips is not None
                          else self.cpr)

    def _controller(self):
        import ray_tpu
        from ray_tpu.serve.api import CONTROLLER_NAME

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _state(self) -> Dict[str, Any]:
        import ray_tpu

        return ray_tpu.get(
            self._controller().pool_state.remote(self.deployment),
            timeout=10)

    def chips(self) -> int:
        return self._state()["routed"] * self.cpr

    def target_chips(self) -> int:
        return self._state()["target"] * self.cpr

    def set_chips(self, chips: int, cause: str,
                  capped: bool = True) -> None:
        import ray_tpu

        replicas = max(int(chips) // self.cpr, 0)
        ray_tpu.get(self._controller().pool_set_replicas.remote(
            self.deployment, replicas,
            cap=replicas if capped else None, cause=cause), timeout=30)

    def clear_cap(self) -> None:
        """Lease fully unwound: give the pressure autoscaler its ceiling
        back (re-issue the current target with no cap)."""
        import ray_tpu

        st = self._state()
        ray_tpu.get(self._controller().pool_set_replicas.remote(
            self.deployment, st["target"], cap=None, cause="pool-uncap"),
            timeout=30)

    def settled(self, chips: int) -> bool:
        """The lease moves ENTITLEMENT (the chip cap); replica usage
        within it stays the serve plane's business. Settled when our cap
        is in force, the controller converged onto its own (possibly
        autoscaler-chosen, cap-bounded) target, and no drain is still
        executing in-flight work — exact-target equality would wedge on
        autoscaled deployments whose pressure policy legitimately moves
        num_replicas below the cap."""
        st = self._state()
        want = max(int(chips) // self.cpr, 0)
        if st["cap"] != want:
            return False  # our entitlement ask is not in force (yet)
        return st["draining"] == 0 and st["routed"] == st["target"] and \
            st["target"] <= want

    def pressure(self) -> Dict[str, float]:
        """Aggregate router/engine pressure for the diurnal policy."""
        import ray_tpu

        snaps = ray_tpu.get(
            self._controller().get_replica_pressure.remote(
                self.deployment), timeout=10)
        ongoing = queue = 0.0
        for s in snaps or []:
            if not s or s.get("unreachable"):
                continue
            ongoing += float(s.get("ongoing") or 0)
            queue += float(s.get("queue_depth") or 0)
        return {"ongoing": ongoing, "queue": queue,
                "replicas": len(snaps or [])}


class TrainWorkload:
    """The elastic trainer's side of a handoff: grow/shrink asks ride
    the preempt pubsub channel as ``world_target`` hints latched by the
    trainer's ResizeGuard; confirmation reads the ``__train__`` KV
    ``world/<run>`` record the controller publishes when each attempt's
    mesh forms."""

    kind = "train"

    def __init__(self, run_name: str, chips_per_worker: int = 1,
                 min_chips: Optional[int] = None):
        self.run = run_name
        self.cpw = max(int(chips_per_worker), 1)
        self.min_chips = (int(min_chips) if min_chips is not None
                          else self.cpw)

    def world(self) -> int:
        from ray_tpu.experimental import internal_kv as kv
        from ray_tpu.train.backend_executor import TRAIN_KV_NS

        raw = kv.internal_kv_get(f"world/{self.run}",
                                 namespace=TRAIN_KV_NS)
        if raw is None:
            return 0
        try:
            rec = json.loads(raw)
        except Exception:  # noqa: BLE001
            return 0
        if rec.get("run_ended"):
            return 0
        return int(rec.get("world", 0))

    def chips(self) -> int:
        return self.world() * self.cpw

    def target_chips(self) -> int:
        # The trainer has no standing spec target outside an attempt:
        # the formed world IS the target.
        return self.chips()

    def set_chips(self, chips: int, cause: str,
                  capped: bool = True) -> None:
        from ray_tpu.train import elastic

        world = max(int(chips) // self.cpw, 1)
        elastic.request_resize(world, reason=f"pool-{cause}")

    def clear_cap(self) -> None:
        pass  # the trainer's ceiling is the ask itself

    def settled(self, chips: int) -> bool:
        return self.world() == max(int(chips) // self.cpw, 1)


# -------------------------------------------------------------- SLO guard

class SloGuard:
    """Serve-SLO watchdog the arbiter consults every tick: between-tick
    deltas of the ingress shed counters and the TTFT / router-latency
    histograms for one deployment. A breach means "do not take serve
    chips now, and give back what the serve plane recently donated"."""

    def __init__(self, deployment: str,
                 shed_rate: Optional[float] = None,
                 ttft_p95_s: Optional[float] = None,
                 latency_p95_s: Optional[float] = None,
                 min_samples: Optional[int] = None):
        def _envf(name, default):
            return float(os.environ.get(name, default))

        self.deployment = deployment
        self.shed_rate = (shed_rate if shed_rate is not None
                          else _envf("RAY_TPU_POOL_SLO_SHED_RATE", "0.05"))
        self.ttft_p95_s = (ttft_p95_s if ttft_p95_s is not None
                           else _envf("RAY_TPU_POOL_SLO_TTFT_P95_S", "0"))
        self.latency_p95_s = (
            latency_p95_s if latency_p95_s is not None
            else _envf("RAY_TPU_POOL_SLO_LATENCY_P95_S", "0"))
        self.min_samples = int(
            min_samples if min_samples is not None
            else _envf("RAY_TPU_POOL_SLO_MIN_SAMPLES", "5"))
        self._prev_shed = self._prev_total = 0.0
        self._prev_buckets: Dict[str, List[int]] = {}
        self._primed = False

    def _counters(self) -> Tuple[float, float]:
        """(sheds, sheds + routed): sheds never route, so routed
        requests are exactly the admitted complement — engine outcome
        counters would double-count every request that also finished."""
        from ray_tpu._private import metrics_defs as mdefs

        shed = mdefs.serve_shed_total(self.deployment)
        routed = 0.0
        for _n, key, v in mdefs.SERVE_REQUESTS.samples():
            if dict(key).get("deployment") == self.deployment:
                routed += v
        return shed, shed + routed

    def _p95_window(self, name: str, hist) -> Optional[float]:
        bounds, counts, _total = hist.bucket_snapshot(
            {"deployment": self.deployment})
        prev = self._prev_buckets.get(name, [0] * len(counts))
        window = [max(c - p, 0) for c, p in zip(counts, prev)]
        self._prev_buckets[name] = counts
        if sum(window) < self.min_samples:
            return None
        return hist.percentile_from(bounds, window, 0.95)

    def check(self) -> Optional[Dict[str, Any]]:
        """One windowed evaluation; the FIRST call only primes the
        cursors (lifetime counters must not read as a fresh regression).
        Returns ``{"signal", "value", "threshold"}`` on breach."""
        from ray_tpu._private import metrics_defs as mdefs

        shed, total = self._counters()
        d_shed = shed - self._prev_shed
        d_total = total - self._prev_total
        self._prev_shed, self._prev_total = shed, total
        ttft_p95 = (self._p95_window("ttft", mdefs.SERVE_REQ_TTFT)
                    if self.ttft_p95_s > 0 else None)
        lat_p95 = (self._p95_window("latency", mdefs.SERVE_LATENCY)
                   if self.latency_p95_s > 0 else None)
        if not self._primed:
            self._primed = True
            return None
        if self.shed_rate > 0 and d_shed > 0 and d_total > 0:
            rate = d_shed / d_total
            if rate >= self.shed_rate:
                return {"signal": "shed_rate", "value": round(rate, 4),
                        "threshold": self.shed_rate}
        if ttft_p95 is not None and ttft_p95 > self.ttft_p95_s:
            return {"signal": "ttft_p95", "value": ttft_p95,
                    "threshold": self.ttft_p95_s}
        if lat_p95 is not None and lat_p95 > self.latency_p95_s:
            return {"signal": "latency_p95", "value": lat_p95,
                    "threshold": self.latency_p95_s}
        return None


# ---------------------------------------------------------------- arbiter

def _envf(name: str, default: str) -> float:
    return float(os.environ.get(name, default))


class ChipPoolArbiter:
    """Head-side reconciler that owns the lease ledger and drives
    handoffs stage by stage. All durable state lives in the journal;
    the arbiter itself can die between any two ticks and a fresh
    instance resumes every lease mid-flight."""

    def __init__(self, serve: ServeWorkload, train: TrainWorkload,
                 kv=None, slo: Optional[SloGuard] = None,
                 policy: str = "diurnal",
                 tick_interval_s: float = 2.0):
        self.serve = serve
        self.train = train
        self.workloads = {"serve": serve, "train": train}
        self.ledger = PoolLedger(kv)
        self.slo = slo if slo is not None else SloGuard(serve.deployment)
        self.policy = policy
        self.tick_interval_s = tick_interval_s
        self.lease_s = _envf("RAY_TPU_POOL_LEASE_S", "900")
        self.stage_timeout_s = _envf("RAY_TPU_POOL_STAGE_TIMEOUT_S", "120")
        self.idle_ticks = int(_envf("RAY_TPU_POOL_IDLE_TICKS", "5"))
        self.step_chips = int(_envf("RAY_TPU_POOL_STEP_CHIPS", "1"))
        self.idle_per_chip = _envf("RAY_TPU_POOL_IDLE_PER_CHIP", "0.1")
        self._idle_streak = 0
        self._tick_no = 0
        # Side effects already issued BY THIS INSTANCE per (lease,
        # stage): a restarted arbiter has an empty set, so it re-issues
        # each parked stage's recorded targets exactly once.
        self._issued: set = set()
        # Last re-nudge time per stuck (lease, stage, field).
        self._nudged: Dict[Tuple, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ledger.bootstrap(
            serve.target_chips(), train.chips(),
            meta={"serve_deployment": serve.deployment,
                  "train_run": train.run})

    # ------------------------------------------------------ public API
    def request_handoff(self, donor: str, chips: int,
                        lease_s: Optional[float] = None) -> str:
        """Journal an explicit handoff intent (the operator/test
        surface; the diurnal policy calls this too). Returns the lease
        id; the next ticks drive it."""
        lease = self.ledger.create_lease(
            donor, "train" if donor == "serve" else "serve",
            chips, lease_s if lease_s is not None else self.lease_s)
        logger.info("pool: lease %s %s->%s chips=%d",
                    lease["lease_id"], lease["donor"],
                    lease["recipient"], chips)
        return lease["lease_id"]

    def status(self) -> Dict[str, Any]:
        return {
            "tick": self._tick_no,
            "allocation": self.ledger.allocation(),
            "leases": self.ledger.leases(),
            "last_reversal": self.ledger.last_reversal(),
            "violations": self.ledger.verify(),
        }

    # ------------------------------------------------------------ tick
    def tick(self) -> Dict[str, Any]:
        from ray_tpu._private import chaos
        from ray_tpu._private import metrics_defs as mdefs

        self._tick_no += 1
        if chaos.enabled():
            # kill_arbiter fires here: the arbiter process dies between
            # journal writes; a fresh instance must resume.
            chaos.inject("pool_tick", tick=self._tick_no)
        breach = self.slo.check() if self.slo is not None else None
        for lease in self.ledger.leases():
            if lease["stage"] in TERMINAL:
                continue
            try:
                self._advance(lease, breach)
            except Exception:  # noqa: BLE001 — one wedged lease must
                logger.exception(   # not stall the others' progress
                    "pool: lease %s advance failed", lease["lease_id"])
        # One post-advance journal snapshot shared by the policy, the
        # invariant check, the gauges, and the returned status (each
        # scan is a full KvKeys + per-lease KvGet in cluster mode).
        leases = self.ledger.leases()
        if self.policy == "diurnal":
            try:
                self._policy(breach, leases)
            except Exception:  # noqa: BLE001
                logger.exception("pool: policy evaluation failed")
        violations = self.ledger.verify(leases)
        for v in violations:
            kind = v.split(":", 1)[0]
            mdefs.POOL_INVARIANT_VIOLATIONS.inc(tags={"kind": kind})
            logger.error("pool: INVARIANT VIOLATION %s", v)
        self._update_gauges(leases)
        return {"tick": self._tick_no, "breach": breach,
                "violations": violations,
                "allocation": self.ledger.allocation(leases)}

    # ----------------------------------------------------- lease drive
    def _chaos_handoff(self, lease: Dict[str, Any]) -> None:
        from ray_tpu._private import chaos

        if chaos.enabled():
            d = chaos.inject("pool_handoff", stage=lease["stage"],
                             lease=lease["lease_id"],
                             direction=f"{lease['donor']}_to_"
                                       f"{lease['recipient']}")
            if d and d.get("preempted_node"):
                # Record the observation with the preemption NOTICE as
                # cause: the same notice also drives the serve drain and
                # the trainer's JIT save, so all three reactions tie back
                # to one chain.
                _events.emit(
                    "pool.handoff_preempted",
                    cause=d.get("notice_id") or d.get("event_id", ""),
                    subject={"lease_id": lease["lease_id"],
                             "node": d["preempted_node"]},
                    stage=lease["stage"])
                logger.warning("pool: node %s preempted mid-handoff "
                               "(lease %s, stage %s)",
                               d["preempted_node"], lease["lease_id"],
                               lease["stage"])

    def _issue(self, lease: Dict[str, Any], workload, target_field: str,
               cause: str, capped: bool = True) -> None:
        """Idempotently (re-)issue a stage's recorded absolute target —
        once per (lease, stage) per arbiter instance, so a restarted
        arbiter repeats the side effect exactly once from the journal.
        Marked issued only AFTER the ask lands: a transient RPC failure
        must retry next tick, not permanently suppress the stage's side
        effect for this instance."""
        key = (lease["lease_id"], lease["stage"], target_field)
        if key in self._issued:
            return
        workload.set_chips(lease[target_field], cause=cause,
                           capped=capped)
        self._issued.add(key)

    def _renudge(self, lease: Dict[str, Any], target_field: str) -> None:
        """A post-commit/rollback stage stopped converging past the
        stage timeout. These stages have no safe rollback (faking
        RETURNED/ABORTED would double-own chips), so re-publish the
        recorded target — the ask may simply have been lost (counterpart
        restarting) — and log loudly instead of wedging silently. At
        most one re-issue per timeout interval."""
        if not self._stage_timed_out(lease):
            return
        key = (lease["lease_id"], lease["stage"], target_field)
        now = time.monotonic()
        if now - self._nudged.get(key, 0.0) < self.stage_timeout_s:
            return
        self._nudged[key] = now
        self._issued.discard(key)
        logger.error(
            "pool: lease %s stuck in %s for %.0fs — re-issuing %s",
            lease["lease_id"], lease["stage"],
            self._stage_age(lease), target_field)

    def _stage_age(self, lease: Dict[str, Any]) -> float:
        return time.time() - lease["history"][-1][1]

    def _stage_timed_out(self, lease: Dict[str, Any]) -> bool:
        return self.stage_timeout_s > 0 and \
            self._stage_age(lease) > self.stage_timeout_s

    def _advance(self, lease: Dict[str, Any],
                 breach: Optional[Dict[str, Any]]) -> None:
        from ray_tpu._private import metrics_defs as mdefs

        donor = self.workloads[lease["donor"]]
        recipient = self.workloads[lease["recipient"]]
        stage = lease["stage"]
        direction = f"{lease['donor']}_to_{lease['recipient']}"
        self._chaos_handoff(lease)

        if stage == PENDING:
            if breach is not None and lease["donor"] == "serve":
                # SLO guard: refuse to take serve chips while the serve
                # plane is already regressing.
                mdefs.POOL_SLO_REVERSALS.inc(tags={
                    "action": "refused", "signal": breach["signal"]})
                rev_ev = self.ledger.record_reversal(
                    lease, "refused", breach["signal"],
                    detail=f"value={breach['value']}")
                self.ledger.advance(lease, ABORTED,
                                    f"slo {breach['signal']}",
                                    cause_event=rev_ev)
                mdefs.POOL_HANDOFFS.inc(tags={"direction": direction,
                                              "outcome": "aborted"})
                return
            donor_target = donor.target_chips() - lease["chips"]
            if donor_target < donor.min_chips:
                self.ledger.advance(lease, ABORTED,
                                    "donor below min_chips")
                mdefs.POOL_HANDOFFS.inc(tags={"direction": direction,
                                              "outcome": "aborted"})
                return
            if recipient.target_chips() < recipient.min_chips:
                # A recipient already below its own floor (e.g. a
                # trainer whose mesh never formed: world 0) could ABSORB
                # the chips but never give them back — the return leg
                # would ask for a sub-floor size that resize cannot
                # express, leaving the chips owned twice. Refuse now.
                self.ledger.advance(lease, ABORTED,
                                    "recipient below min_chips — "
                                    "lease could not be returned")
                mdefs.POOL_HANDOFFS.inc(tags={"direction": direction,
                                              "outcome": "aborted"})
                return
            lease = self.ledger.advance(
                lease, FREEING, f"donor -> {donor_target} chips",
                donor_target=donor_target)
            self._issue(lease, donor, "donor_target", "pool-free")
            return

        if stage == FREEING:
            self._issue(lease, donor, "donor_target", "pool-free")
            if donor.settled(lease["donor_target"]):
                self.ledger.advance(lease, FREED, "donor confirmed")
            elif self._stage_timed_out(lease):
                self._abort(lease, "FREEING timed out")
            return

        if stage == FREED:
            recip_target = recipient.target_chips() + lease["chips"]
            lease = self.ledger.advance(
                lease, GRANTING, f"recipient -> {recip_target} chips",
                recipient_target=recip_target)
            self._issue(lease, recipient, "recipient_target",
                        "pool-grant")
            return

        if stage == GRANTING:
            self._issue(lease, recipient, "recipient_target",
                        "pool-grant")
            if recipient.settled(lease["recipient_target"]):
                now = time.time()
                lease = self.ledger.advance(
                    lease, COMMITTED, "recipient confirmed",
                    deadline_ts=now + lease["lease_s"])
                mdefs.POOL_HANDOFFS.inc(tags={"direction": direction,
                                              "outcome": "committed"})
                mdefs.POOL_HANDOFF_SECONDS.observe(
                    now - lease["created_ts"],
                    tags={"direction": direction})
            elif self._stage_timed_out(lease):
                self._abort(lease, "GRANTING timed out")
            return

        if stage == COMMITTED:
            if breach is not None and lease["donor"] == "serve":
                # Morning load: reverse the committed handoff — the
                # serve plane gets its chips back.
                mdefs.POOL_SLO_REVERSALS.inc(tags={
                    "action": "reversed", "signal": breach["signal"]})
                rev_ev = self.ledger.record_reversal(
                    lease, "reversed", breach["signal"],
                    detail=f"value={breach['value']}")
                self._begin_return(lease, f"slo {breach['signal']}",
                                   cause_event=rev_ev)
            elif lease["deadline_ts"] is not None and \
                    time.time() > lease["deadline_ts"]:
                self._begin_return(lease, "lease deadline lapsed")
            return

        if stage == RETURN_FREEING:
            self._renudge(lease, "return_recipient_target")
            self._issue(lease, recipient, "return_recipient_target",
                        "pool-return-free")
            if recipient.settled(lease["return_recipient_target"]):
                donor_restore = donor.target_chips() + lease["chips"]
                lease = self.ledger.advance(
                    lease, RETURN_GRANTING,
                    f"donor restore -> {donor_restore} chips",
                    return_donor_target=donor_restore)
                self._issue(lease, donor, "return_donor_target",
                            "pool-return-grant")
            return

        if stage == RETURN_GRANTING:
            self._renudge(lease, "return_donor_target")
            self._issue(lease, donor, "return_donor_target",
                        "pool-return-grant")
            if donor.settled(lease["return_donor_target"]):
                self.ledger.advance(lease, RETURNED, "chips returned")
                mdefs.POOL_HANDOFFS.inc(tags={"direction": direction,
                                              "outcome": "returned"})
                self._maybe_uncap(lease)
            return

        if stage == ABORTING:
            self._renudge(lease, "abort_donor_target")
            if lease.get("abort_recipient_target") is not None:
                # Undo the grant ask (journaled, so a crash between the
                # ABORTING write and this publish re-issues it here on
                # restart). Best-effort and NOT gated on: the recipient
                # failing to settle is usually WHY we are aborting.
                try:
                    self._issue(lease, recipient,
                                "abort_recipient_target", "pool-abort")
                except Exception:  # noqa: BLE001 — donor restore wins
                    logger.exception("pool: abort un-grant failed")
            self._issue(lease, donor, "abort_donor_target",
                        "pool-abort")
            if donor.settled(lease["abort_donor_target"]):
                self.ledger.advance(lease, ABORTED, "rolled back")
                mdefs.POOL_HANDOFFS.inc(tags={"direction": direction,
                                              "outcome": "aborted"})
                self._maybe_uncap(lease)
            return

    def _begin_return(self, lease: Dict[str, Any], detail: str,
                      cause_event: str = "") -> None:
        recipient = self.workloads[lease["recipient"]]
        give_back = recipient.target_chips() - lease["chips"]
        lease = self.ledger.advance(
            lease, RETURN_FREEING, detail, cause_event=cause_event,
            return_recipient_target=give_back)
        self._issue(lease, recipient, "return_recipient_target",
                    "pool-return-free")

    def _abort(self, lease: Dict[str, Any], detail: str) -> None:
        """Roll a pre-commit lease back: journal BOTH restore targets in
        the ABORTING record first (a crash right after this write still
        re-issues them on restart), then let the ABORTING handler fire
        the side effects."""
        donor = self.workloads[lease["donor"]]
        restore = lease.get("donor_target",
                            donor.target_chips()) + lease["chips"]
        fields: Dict[str, Any] = {"abort_donor_target": restore}
        if lease.get("recipient_target") is not None:
            fields["abort_recipient_target"] = \
                lease["recipient_target"] - lease["chips"]
        lease = self.ledger.advance(lease, ABORTING, detail, **fields)
        self._advance(lease, None)  # fire the ABORTING side effects now

    def _maybe_uncap(self, lease: Dict[str, Any]) -> None:
        """After a lease fully unwinds, lift the serve chip cap when no
        other live lease still holds serve chips."""
        live = [rec for rec in self.ledger.leases()
                if rec["stage"] not in TERMINAL
                and rec["lease_id"] != lease["lease_id"]
                and "serve" in (rec["donor"], rec["recipient"])]
        if not live:
            try:
                self.serve.clear_cap()
            except Exception:  # noqa: BLE001 — cap lifts on next unwind
                logger.exception("pool: clear_cap failed")

    # ---------------------------------------------------------- policy
    def _policy(self, breach: Optional[Dict[str, Any]],
                leases: Optional[List[Dict[str, Any]]] = None) -> None:
        """The diurnal closed loop: serve idle for ``idle_ticks``
        consecutive ticks -> lease ``step_chips`` to training (never
        below the serve floor); an SLO breach reverses the newest
        committed serve->train lease (the COMMITTED handler journals the
        reversal) and blocks new takes."""
        if breach is not None:
            self._idle_streak = 0
            return
        if leases is None:
            leases = self.ledger.leases()
        in_flight = [rec for rec in leases
                     if rec["stage"] in TRANSITIONAL
                     or rec["stage"] == PENDING]
        if in_flight:
            return  # one handoff at a time keeps confirmation crisp
        p = self.serve.pressure()
        chips = max(self.serve.target_chips(), 1)
        idle = (p["ongoing"] + p["queue"]) <= self.idle_per_chip * chips
        self._idle_streak = self._idle_streak + 1 if idle else 0
        if self._idle_streak < self.idle_ticks:
            return
        surplus = self.serve.target_chips() - self.serve.min_chips
        take = min(self.step_chips, surplus)
        if take > 0:
            self.request_handoff("serve", take)
            self._idle_streak = 0

    # --------------------------------------------------------- metrics
    def _update_gauges(self, leases: Optional[List[Dict[str, Any]]] = None
                       ) -> None:
        from ray_tpu._private import metrics_defs as mdefs

        if leases is None:
            leases = self.ledger.leases()
        alloc = self.ledger.allocation(leases)
        for owner in ("serve", "train", "in_flight"):
            mdefs.POOL_CHIPS.set(float(alloc[owner]),
                                 tags={"owner": owner})
        counts: Dict[str, int] = {}
        for lease in leases:
            if lease["stage"] not in TERMINAL:
                counts[lease["stage"]] = counts.get(lease["stage"], 0) + 1
        for stage in _LEASE_TRANSITIONS:
            if stage in TERMINAL:
                continue
            mdefs.POOL_LEASES.set(float(counts.get(stage, 0)),
                                  tags={"stage": stage})

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="chip-pool-arbiter")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001
                logger.exception("pool: tick failed")

    def stop(self) -> None:
        self._stop.set()


# -------------------------------------------------- offline state readers

def read_pool_state(gcs_address: Optional[str] = None) -> Dict[str, Any]:
    """Pool snapshot for the CLI/dashboard: config, allocation, leases
    (non-terminal first), in-flight handoffs, and the last SLO-guard
    reversal. With ``gcs_address`` this talks straight to the GCS KV (no
    runtime needed — the ``ray-tpu ckpt list`` offline-friendly style);
    without one it reads the connected/in-process KV."""
    if gcs_address:
        from ray_tpu._private import rpc
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        gcs = rpc.get_stub("GcsService", gcs_address)

        def _get(key):
            r = gcs.KvGet(pb.KvRequest(ns=POOL_KV_NS, key=key))
            return bytes(r.value) if r.found else None

        def _keys(prefix):
            return list(gcs.KvKeys(pb.KvRequest(ns=POOL_KV_NS,
                                                prefix=prefix)).keys)
    else:
        store = InternalKv()
        _get, _keys = store.get, store.keys

    def _load(key):
        raw = _get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except Exception:  # noqa: BLE001
            return None

    config = _load("config")
    leases = [rec for rec in (_load(k) for k in sorted(_keys("lease/")))
              if rec is not None]
    leases.sort(key=lambda r: (r["stage"] in TERMINAL, -r["created_ts"]))
    out: Dict[str, Any] = {
        "config": config,
        "leases": leases,
        "in_flight": [r for r in leases
                      if r["stage"] in TRANSITIONAL
                      or r["stage"] == PENDING],
        "last_reversal": _load("last_reversal"),
    }
    out["allocation"] = (compute_allocation(config, leases)
                         if config else None)
    return out


__all__ = [
    "ChipPoolArbiter", "DictKv", "InternalKv", "InvalidLeaseTransition",
    "PoolLedger", "ServeWorkload", "SloGuard", "TrainWorkload",
    "compute_allocation", "read_pool_state",
    "PENDING", "FREEING", "FREED", "GRANTING", "COMMITTED",
    "RETURN_FREEING", "RETURN_GRANTING", "RETURNED", "ABORTING",
    "ABORTED", "TERMINAL", "TRANSITIONAL", "POOL_KV_NS",
]
