"""Autoscaler monitor process (reference: ``autoscaler/_private/
monitor.py:127`` — the head-side daemon running the reconcile loop).

Launched by ``ray-tpu up`` when the cluster config enables autoscaling:
reads cluster load from the GCS each tick and drives a
:class:`~ray_tpu.autoscaler.LocalNodeProvider` (or any provider named in
the config) to launch/terminate worker nodes.
"""

from __future__ import annotations

import argparse
import json
import logging
import time


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--config", required=True,
                        help="JSON cluster config (worker defaults + "
                             "min/max workers)")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    cfg = json.loads(args.config)

    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider

    pcfg = cfg.get("provider", {})
    if pcfg.get("type") == "gcp_tpu":
        # Cloud provisioning: TPU VM slices via the Cloud TPU REST API.
        from ray_tpu.autoscaler.gcp import GceHttp, TPUNodeProvider

        # Auth, in preference order: token_file (re-read per call, so an
        # external refresher can rotate it — OAuth bearer tokens expire
        # hourly), static token (tests/short-lived runs), else the GCE
        # metadata server (the on-GCP default, which self-refreshes).
        token_file = pcfg.get("token_file")
        token = pcfg.get("token")
        if token_file:
            def token_provider(path=token_file):
                with open(path) as tf:
                    return tf.read().strip()
        elif token:
            def token_provider(tok=token):
                return tok
        else:
            token_provider = None
        http = GceHttp(endpoint=pcfg.get("endpoint",
                                         "https://tpu.googleapis.com/v2"),
                       token_provider=token_provider)
        provider = TPUNodeProvider(
            pcfg["project"], pcfg["zone"],
            pcfg.get("cluster_name", "ray-tpu"),
            config=cfg.get("worker", {}), http=http)
    else:
        provider = LocalNodeProvider(args.gcs_address,
                                     defaults=cfg.get("worker", {}))
    scaler = Autoscaler(
        args.gcs_address, provider,
        node_config=cfg.get("worker", {}),
        min_workers=int(cfg.get("min_workers", 0)),
        max_workers=int(cfg.get("max_workers", 4)),
        idle_timeout_s=float(cfg.get("idle_timeout_s", 60.0)))
    # `ray-tpu down` SIGTERMs this process; the provider's node-manager
    # subprocesses are OUR children and must die with us or they'd run on
    # as orphans holding ports.
    import signal
    import sys

    def _shutdown(*_):
        if hasattr(provider, "terminate_all"):
            provider.terminate_all()
        sys.exit(0)

    signal.signal(signal.SIGTERM, _shutdown)
    scaler.start()
    print("MONITOR_STARTED=1", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        if hasattr(provider, "terminate_all"):
            provider.terminate_all()


if __name__ == "__main__":  # pragma: no cover
    main()
