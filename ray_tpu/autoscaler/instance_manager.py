"""Instance lifecycle state machine for autoscaled nodes.

Reference: ``python/ray/autoscaler/v2/instance_manager/instance_manager.py:29``
— the v2 autoscaler tracks every cloud instance through an explicit status
machine (QUEUED → REQUESTED → ALLOCATED → RAY_RUNNING → TERMINATING →
TERMINATED, with failure branches), keeping a per-instance transition
history so scaling decisions and debugging read from recorded state
instead of re-deriving it from provider list calls.

This build's reconciler (:class:`~ray_tpu.autoscaler.Autoscaler`) drives
the same transitions against the provider + GCS views; the
InstanceManager is the bookkeeping layer: it owns the instance table,
validates transitions, and records history. Providers stay the simple
three-method ABC.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# Statuses (reference: instance_manager.proto Instance.Status).
QUEUED = "QUEUED"                    # asked for, not yet requested
REQUESTED = "REQUESTED"              # provider.create_node in flight
ALLOCATED = "ALLOCATED"              # cloud says the instance exists
RAY_RUNNING = "RAY_RUNNING"          # node registered with the GCS
RAY_STOPPING = "RAY_STOPPING"        # drain requested
TERMINATING = "TERMINATING"          # provider.terminate_node in flight
TERMINATED = "TERMINATED"            # gone (terminal)
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # provider create failed (terminal)

# Legal transitions (anything else is a bug worth failing loudly on).
_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RAY_RUNNING, TERMINATING, TERMINATED},
    RAY_RUNNING: {RAY_STOPPING, TERMINATING, TERMINATED},
    RAY_STOPPING: {RAY_RUNNING, TERMINATING, TERMINATED},
    TERMINATING: {TERMINATED},
    TERMINATED: set(),
    ALLOCATION_FAILED: set(),
}


@dataclass
class Instance:
    instance_id: str
    status: str = QUEUED
    node_config: Dict[str, Any] = field(default_factory=dict)
    provider_id: str = ""            # cloud/provider node id once requested
    node_id: str = ""                # GCS node id once registered
    created_at: float = field(default_factory=time.monotonic)
    updated_at: float = field(default_factory=time.monotonic)
    # [(status, monotonic ts, detail)] — full transition history.
    history: List[tuple] = field(default_factory=list)


class InvalidTransition(RuntimeError):
    pass


class InstanceManager:
    """Instance table + transition validation + provider actions.

    ``launch_instances`` / ``terminate_instance`` perform the provider
    side effects AND record the state transitions; ``sync_from`` folds in
    the externally-observed views (provider inventory, GCS nodes) each
    reconcile tick.
    """

    def __init__(self, provider):
        self.provider = provider
        self._instances: Dict[str, Instance] = {}
        self._lock = threading.Lock()

    # ----------------------------------------------------------- accessors
    def instances(self, statuses: Optional[set] = None) -> List[Instance]:
        with self._lock:
            out = list(self._instances.values())
        if statuses is not None:
            out = [i for i in out if i.status in statuses]
        return out

    TERMINAL = frozenset({TERMINATED, ALLOCATION_FAILED})

    def get_by_provider_id(self, provider_id: str) -> Optional[Instance]:
        """The LIVE instance for a provider id. Terminal instances are
        skipped: a TERMINATED record must not shadow the id, or a node
        whose terminate call failed transiently could never be
        re-terminated through the manager."""
        with self._lock:
            for inst in self._instances.values():
                if inst.provider_id == provider_id and \
                        inst.status not in self.TERMINAL:
                    return inst
        return None

    # --------------------------------------------------------- transitions
    def _set_status(self, inst: Instance, status: str,
                    detail: str = "") -> None:
        if status not in _TRANSITIONS.get(inst.status, set()):
            raise InvalidTransition(
                f"instance {inst.instance_id}: {inst.status} -> {status}")
        inst.status = status
        inst.updated_at = time.monotonic()
        inst.history.append((status, inst.updated_at, detail))

    # -------------------------------------------------------------- actions
    def launch_instances(self, count: int,
                         node_config: Dict[str, Any]) -> List[Instance]:
        """QUEUED → REQUESTED → ALLOCATED/ALLOCATION_FAILED for ``count``
        new instances (our provider ABC's create_node is synchronous, so
        REQUESTED exists in the history rather than as a resting state)."""
        self._prune()
        launched = []
        for _ in range(count):
            inst = Instance(instance_id=f"inst-{uuid.uuid4().hex[:12]}",
                            node_config=dict(node_config))
            inst.history.append((QUEUED, inst.created_at, ""))
            with self._lock:
                self._instances[inst.instance_id] = inst
            self._set_status(inst, REQUESTED)
            try:
                from ray_tpu._private import chaos

                if chaos.enabled():
                    # fail_create_node: a cloud allocation failure
                    # (quota/stockout) raised exactly where the provider
                    # would — the instance lands in ALLOCATION_FAILED and
                    # the reconciler's launch backoff takes over.
                    chaos.inject("provider_create",
                                 provider=type(self.provider).__name__)
                inst.provider_id = self.provider.create_node(node_config)
                self._set_status(inst, ALLOCATED, inst.provider_id)
            except Exception as e:  # noqa: BLE001
                self._set_status(inst, ALLOCATION_FAILED, str(e))
                logger.warning("instance %s allocation failed: %s",
                               inst.instance_id, e)
                continue
            launched.append(inst)
        return launched

    def terminate_instance(self, instance_id: str,
                           detail: str = "") -> bool:
        with self._lock:
            inst = self._instances.get(instance_id)
        if inst is None or inst.status in (TERMINATED, ALLOCATION_FAILED):
            return False
        if inst.status == QUEUED:
            self._set_status(inst, TERMINATED, detail or "cancelled")
            return True
        if inst.status != TERMINATING:
            self._set_status(inst, TERMINATING, detail)
        try:
            self.provider.terminate_node(inst.provider_id)
        except Exception as e:  # noqa: BLE001
            # Stay TERMINATING: the next reconcile tick retries (marking
            # TERMINATED on a failed call would leak the cloud node).
            logger.warning("terminate of %s failed (will retry): %s",
                           inst.provider_id, e)
            return False
        self._set_status(inst, TERMINATED, detail)
        self._prune()
        return True

    MAX_TERMINAL_KEPT = 512

    def _prune(self) -> None:
        """Bound the table: keep only the newest terminal records (a
        long-lived reconciler retrying against a quota-exhausted provider
        would otherwise grow one ALLOCATION_FAILED instance per tick)."""
        with self._lock:
            terminal = [i for i in self._instances.values()
                        if i.status in self.TERMINAL]
            excess = len(terminal) - self.MAX_TERMINAL_KEPT
            if excess > 0:
                terminal.sort(key=lambda i: i.updated_at)
                for inst in terminal[:excess]:
                    del self._instances[inst.instance_id]

    # ---------------------------------------------------------------- sync
    def sync_from(self, provider_ids: set, gcs_provider_ids: set) -> None:
        """Fold in observed state: provider inventory (which instances
        still exist) and the GCS view (which registered as nodes).

        ALLOCATED + seen in GCS → RAY_RUNNING; any non-terminal instance
        that vanished from the provider → TERMINATED (preempted/deleted
        externally)."""
        with self._lock:
            insts = list(self._instances.values())
        for inst in insts:
            # REQUESTED skipped too: an instance observed mid-launch has
            # no provider_id yet and must not take the vanished branch.
            if inst.status in (TERMINATED, ALLOCATION_FAILED, QUEUED,
                               REQUESTED):
                continue
            if inst.provider_id not in provider_ids:
                self._set_status(inst, TERMINATED, "vanished from provider")
                continue
            if inst.status == ALLOCATED and \
                    inst.provider_id in gcs_provider_ids:
                self._set_status(inst, RAY_RUNNING)
            elif inst.status == RAY_RUNNING and \
                    inst.provider_id not in gcs_provider_ids:
                # Registered once, gone from the GCS now: draining/dead
                # ray-side while the VM lives on.
                self._set_status(inst, RAY_STOPPING, "left the GCS")
            elif inst.status == RAY_STOPPING and \
                    inst.provider_id in gcs_provider_ids:
                # Back in the GCS (heartbeat blip / cancelled drain).
                self._set_status(inst, RAY_RUNNING, "re-registered")

    def summary(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for inst in self.instances():
            counts[inst.status] = counts.get(inst.status, 0) + 1
        return counts


__all__ = ["Instance", "InstanceManager", "InvalidTransition",
           "QUEUED", "REQUESTED", "ALLOCATED", "RAY_RUNNING",
           "RAY_STOPPING", "TERMINATING", "TERMINATED",
           "ALLOCATION_FAILED"]
