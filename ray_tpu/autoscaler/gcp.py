"""GCP TPU-VM node provider: provisions Cloud TPU VMs over the REST API.

Reference: ``python/ray/autoscaler/_private/gcp/node_provider.py`` (+
``gcp/node.py`` — the ``GCPTPUNode`` resource wrapper) redesigned
TPU-first: the unit of provisioning is a *TPU pod slice* (one
``nodes.create`` call may back several hosts), created nodes carry the
cluster name as a label, and readiness is the TPU ``READY`` state plus the
operation-done poll. The HTTP transport is a tiny injectable client so
tests drive the provider against a recorded/mock endpoint
(``tests/test_autoscaler.py``) with byte-identical request shapes.

Bootstrap: each created TPU VM is expected to start a ray_tpu node that
registers with the GCS carrying the label ``provider-node-id:<name>`` —
the autoscaler joins provider inventory to GCS nodes through that label
(the reference matches through instance metadata).
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler import NodeProvider

logger = logging.getLogger(__name__)

TPU_API = "https://tpu.googleapis.com/v2"
CLUSTER_LABEL = "ray-tpu-cluster"


class GceHttp:
    """Minimal authenticated JSON-over-HTTP client for the TPU/GCE APIs.

    ``token_provider`` returns a bearer token (the real path reads the GCE
    metadata server; tests pass a constant). Injectable so unit tests run
    against a local mock endpoint with zero cloud access.
    """

    def __init__(self, endpoint: str = TPU_API, token_provider=None,
                 timeout_s: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self._token_provider = token_provider or _metadata_token
        self.timeout_s = timeout_s

    def request(self, method: str, path: str,
                body: Optional[dict] = None) -> dict:
        url = f"{self.endpoint}/{path.lstrip('/')}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Authorization", f"Bearer {self._token_provider()}")
        req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            raise RuntimeError(
                f"{method} {url} failed: {e.code} "
                f"{e.read().decode(errors='replace')[:500]}") from None
        return json.loads(payload) if payload else {}


def _metadata_token() -> str:
    """Bearer token from the GCE metadata server (only reachable on GCP)."""
    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())["access_token"]


class TPUNodeProvider(NodeProvider):
    """Provision/terminate TPU VM slices for one named cluster.

    ``node_config`` keys (per create): ``accelerator_type`` (e.g.
    "v5litepod-8"), ``runtime_version``, ``labels``, ``startup_script``.
    Defaults come from the provider-level config.
    """

    OP_POLL_S = 2.0
    OP_TIMEOUT_S = 600.0

    def __init__(self, project: str, zone: str, cluster_name: str,
                 config: Optional[Dict[str, Any]] = None,
                 http: Optional[GceHttp] = None):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.config = dict(config or {})
        self.http = http or GceHttp()
        self._counter = 0

    # ------------------------------------------------------------- helpers
    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _node_body(self, node_config: Dict[str, Any]) -> dict:
        cfg = {**self.config, **(node_config or {})}
        labels = {CLUSTER_LABEL: self.cluster_name,
                  **cfg.get("labels", {})}
        body = {
            "acceleratorType": cfg.get("accelerator_type", "v5litepod-8"),
            "runtimeVersion": cfg.get("runtime_version",
                                      "tpu-ubuntu2204-base"),
            "labels": labels,
        }
        if cfg.get("startup_script"):
            body["metadata"] = {"startup-script": cfg["startup_script"]}
        if cfg.get("network"):
            body["networkConfig"] = {"network": cfg["network"]}
        return body

    def _wait_operation(self, op: dict) -> dict:
        """Poll a long-running operation to completion (reference:
        ``gcp/node.py`` wait_for_operation)."""
        name = op.get("name")
        if not name or op.get("done"):
            return op
        deadline = time.monotonic() + self.OP_TIMEOUT_S
        while time.monotonic() < deadline:
            op = self.http.request("GET", name)
            if op.get("done"):
                if op.get("error"):
                    raise RuntimeError(
                        f"TPU operation {name} failed: {op['error']}")
                return op
            time.sleep(self.OP_POLL_S)
        raise TimeoutError(f"TPU operation {name} did not finish")

    # ------------------------------------------------------------ interface
    def create_node(self, node_config: Dict[str, Any]) -> str:
        self._counter += 1
        node_id = (f"{self.cluster_name}-worker-"
                   f"{int(time.time())}-{self._counter}")
        op = self.http.request(
            "POST", f"{self._parent}/nodes?nodeId={node_id}",
            self._node_body(node_config))
        self._wait_operation(op)
        logger.info("created TPU VM %s (%s)", node_id,
                    self._node_body(node_config)["acceleratorType"])
        return node_id

    def terminate_node(self, node_id: str) -> None:
        try:
            op = self.http.request(
                "DELETE", f"{self._parent}/nodes/{node_id}")
            self._wait_operation(op)
        except RuntimeError as e:
            if "404" in str(e):
                return  # already gone
            raise

    def terminate_all(self) -> None:
        """Tear down every VM of this cluster (``ray-tpu down``): leaving
        provisioned TPU VMs running with no autoscaler to reclaim them
        would bill forever."""
        for node_id in self.non_terminated_nodes():
            try:
                self.terminate_node(node_id)
            except Exception:  # noqa: BLE001 — best-effort teardown
                logger.exception("failed to terminate %s", node_id)

    def non_terminated_nodes(self) -> List[str]:
        reply = self.http.request("GET", f"{self._parent}/nodes")
        out = []
        for node in reply.get("nodes", []):
            labels = node.get("labels", {})
            if labels.get(CLUSTER_LABEL) != self.cluster_name:
                continue
            if node.get("state") in ("READY", "CREATING", "STARTING"):
                # name is fully qualified: projects/.../nodes/<id>
                out.append(node.get("name", "").rsplit("/", 1)[-1])
        return out

    def node_ips(self, node_id: str) -> List[str]:
        """Worker-host IPs of a slice (multi-host slices list every VM)."""
        node = self.http.request("GET", f"{self._parent}/nodes/{node_id}")
        return [ep.get("ipAddress", "")
                for ep in node.get("networkEndpoints", [])]


__all__ = ["TPUNodeProvider", "GceHttp", "CLUSTER_LABEL", "TPU_API"]
