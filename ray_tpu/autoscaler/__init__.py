"""Autoscaler: provider-backed cluster scaling reconciler.

Reference: ``python/ray/autoscaler`` — v2's reconciler shape
(``v2/autoscaler.py:42`` + ``instance_manager``): each tick reads the
cluster's state (alive nodes, utilization, explicit resource requests) and
drives the node count toward the target through a ``NodeProvider``
(``autoscaler/node_provider.py:13``). ``FakeNodeProvider`` mirrors the
reference's fake_multi_node provider (node_provider.py:236): nodes are
in-process NodeManagers, so scaling logic is testable with no cloud.

Explicit demand (``request_resources`` —
``ray.autoscaler.sdk.request_resources``) is stored in the GCS KV so any
client can post it.
"""

from __future__ import annotations

import abc
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu.protobuf import ray_tpu_pb2 as pb

logger = logging.getLogger(__name__)

KV_NS = "autoscaler"


class NodeProvider(abc.ABC):
    @abc.abstractmethod
    def create_node(self, node_config: Dict[str, Any]) -> str: ...

    @abc.abstractmethod
    def terminate_node(self, node_id: str) -> None: ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[str]: ...


class FakeNodeProvider(NodeProvider):
    """Nodes are in-process NodeManagers (reference FakeMultiNodeProvider)."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._nodes: Dict[str, Any] = {}

    def create_node(self, node_config: Dict[str, Any]) -> str:
        from ray_tpu._private.node_manager.server import NodeManager

        nm = NodeManager(self.gcs_address,
                         resources=dict(node_config.get("resources",
                                                        {"CPU": 4.0})),
                         labels=node_config.get("labels"))
        self._nodes[nm.node_id] = nm
        return nm.node_id

    def terminate_node(self, node_id: str) -> None:
        nm = self._nodes.pop(node_id, None)
        if nm is not None:
            nm.shutdown()

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)


class LocalNodeProvider(NodeProvider):
    """Nodes are node-manager SUBPROCESSES on this host — the
    cluster-launcher provider for single-machine clusters (reference: the
    local node provider under ``autoscaler/_private``; cloud providers
    slot in through the same three-method ABC)."""

    def __init__(self, gcs_address: str,
                 defaults: Optional[Dict[str, Any]] = None):
        self.gcs_address = gcs_address
        self.defaults = defaults or {}
        self._procs: Dict[str, Any] = {}

    def create_node(self, node_config: Dict[str, Any]) -> str:
        import json as _json
        import subprocess
        import sys

        cfg = {**self.defaults, **(node_config or {})}
        resources = dict(cfg.get("resources", {}))
        num_cpus = float(resources.pop("CPU", cfg.get("num_cpus", 4)))
        num_tpus = cfg.get("num_tpus")
        cmd = [sys.executable, "-m",
               "ray_tpu._private.node_manager.server",
               "--gcs-address", self.gcs_address,
               "--num-cpus", str(num_cpus),
               "--num-tpus", str(-1 if num_tpus is None else num_tpus),
               "--resources", _json.dumps(resources),
               "--labels", _json.dumps(cfg.get("labels", {}))]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(filter(None, (
            list(sys.path) + [env.get("PYTHONPATH", "")]))))
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, env=env,
                                text=True)
        node_id = None
        deadline = time.monotonic() + 60.0
        while node_id is None:
            line = proc.stdout.readline().strip()
            if line.startswith("NODE_ID="):
                node_id = line.split("=", 1)[1]
            elif not line and proc.poll() is not None:
                raise RuntimeError("worker node process died at startup")
            elif time.monotonic() > deadline:
                proc.terminate()
                raise RuntimeError("worker node start timed out")
        self._procs[node_id] = proc
        return node_id

    def terminate_all(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        if proc is not None:
            proc.terminate()

    def non_terminated_nodes(self) -> List[str]:
        dead = [nid for nid, p in self._procs.items()
                if p.poll() is not None]
        for nid in dead:
            self._procs.pop(nid, None)
        return list(self._procs)

    def pids(self) -> List[int]:
        return [p.pid for p in self._procs.values()]


def request_resources(gcs_address: str,
                      bundles: List[Dict[str, float]]) -> None:
    """Post an explicit resource ask the autoscaler must satisfy."""
    gcs = rpc.get_stub("GcsService", gcs_address)
    gcs.KvPut(pb.KvRequest(ns=KV_NS, key="requests",
                           value=json.dumps(bundles).encode(),
                           overwrite=True))


class Autoscaler:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 node_config: Optional[Dict[str, Any]] = None,
                 min_workers: int = 0, max_workers: int = 8,
                 target_utilization: float = 0.8,
                 idle_timeout_s: float = 30.0,
                 tick_interval_s: float = 1.0):
        from ray_tpu.autoscaler.instance_manager import InstanceManager

        self.gcs = rpc.get_stub("GcsService", gcs_address)
        self.provider = provider
        # Instance lifecycle bookkeeping (reference: the v2
        # InstanceManager): every launch/terminate this reconciler makes
        # runs through the state machine, and reconcile ticks fold the
        # provider + GCS views back into it.
        self.im = InstanceManager(provider)
        self.node_config = node_config or {"resources": {"CPU": 4.0}}
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.target_utilization = target_utilization
        self.idle_timeout_s = idle_timeout_s
        self.tick_interval_s = tick_interval_s
        self._idle_since: Dict[str, float] = {}
        self._unregistered_since: Dict[str, float] = {}
        self._warned_infeasible: set = set()
        # Allocation-failure backoff: a failed provider create opens an
        # exponential launch-suppression window (retrying a quota-
        # exhausted provider at full tick rate hammers its API and fills
        # the instance table with ALLOCATION_FAILED records).
        self._alloc_fail_streak = 0
        self._alloc_backoff_until = 0.0
        self._alloc_backoff_base_s = float(os.environ.get(
            "RAY_TPU_AUTOSCALER_ALLOC_BACKOFF_S", "2.0"))
        self._alloc_backoff_max_s = float(os.environ.get(
            "RAY_TPU_AUTOSCALER_ALLOC_BACKOFF_MAX_S", "60.0"))
        # Tick-loop failure accounting: consecutive raised ticks back the
        # interval off and the last error is surfaced in summary() / the
        # dashboard instead of only the head-node log.
        self._tick_fail_streak = 0
        self._last_tick_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def _provider_tag(self) -> str:
        return type(self.provider).__name__

    # ----------------------------------------------------------------- logic
    def _demand_bundles(self) -> List[Dict[str, float]]:
        reply = self.gcs.KvGet(pb.KvRequest(ns=KV_NS, key="requests"))
        if not reply.found:
            return []
        return json.loads(reply.value)

    # A provider node that never registers with the GCS within this window
    # failed its bootstrap; reclaim it (reference: node launch failure
    # handling in the v2 InstanceManager reconciler).
    UNREGISTERED_GRACE_S = 300.0

    def _provider_id_of(self, node) -> Optional[str]:
        """GCS node -> provider inventory id. In-process providers register
        under their own node_id; cloud nodes carry the provider-node-id
        label their bootstrap was launched with. Several GCS nodes may map
        to ONE provider id (a multi-host TPU slice is one provider node)."""
        return dict(node.labels).get("provider-node-id") or node.node_id

    @staticmethod
    def _try_place(pools: List[Dict[str, float]],
                   bundle: Dict[str, float]) -> bool:
        """Place ``bundle`` onto the first pool that fits, mutating it."""
        for a in pools:
            if all(a.get(k, 0.0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    a[k] -= v
                return True
        return False

    def _bundle_fits_shape(self, bundle: Dict[str, float]) -> bool:
        shape = self.node_config.get("resources", {"CPU": 4.0})
        return all(shape.get(k, 0.0) >= v for k, v in bundle.items())

    def _pack_nodes_needed(self, bundles: List[Dict[str, float]]) -> int:
        """FFD bin-packing: the FEWEST node_config-shaped nodes that cover
        the unplaced demand (reference:
        ``resource_demand_scheduler.get_nodes_for``). One-node-per-bundle
        over-launched 8x for 8 single-chip asks on an 8-chip host."""
        shape = dict(self.node_config.get("resources", {"CPU": 4.0}))
        nodes: List[Dict[str, float]] = []
        for bundle in sorted(bundles, key=lambda b: -sum(b.values())):
            if not self._try_place(nodes, bundle):
                fresh = dict(shape)
                for k, v in bundle.items():
                    fresh[k] -= v
                nodes.append(fresh)
        return len(nodes)

    def reconcile_once(self) -> Dict[str, Any]:
        """One tick: returns {"launched": n, "terminated": m,
        "instances": {status: count}} (the instance-table summary)."""
        nodes = [n for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes
                 if n.alive]
        managed = set(self.provider.non_terminated_nodes())
        # pid -> every GCS node backing it (multi-host slices have many).
        groups: Dict[str, List[Any]] = {}
        for n in nodes:
            pid = self._provider_id_of(n)
            if pid in managed:
                groups.setdefault(pid, []).append(n)
        # Fold observed state into the instance table (ALLOCATED nodes
        # that registered become RAY_RUNNING; vanished ones TERMINATED).
        self.im.sync_from(managed, set(groups))
        launched = terminated = 0

        # 1) explicit resource requests: place onto current free capacity
        #    first, then bin-pack the remainder onto the fewest new nodes.
        #    Bundles no node shape can EVER satisfy are reported and
        #    excluded — they must not wedge scale-down forever.
        unfit: List[Dict[str, float]] = []
        avail = [dict(n.available) for n in nodes]
        for bundle in self._demand_bundles():
            if not self._try_place(avail, bundle):
                if self._bundle_fits_shape(bundle):
                    unfit.append(bundle)
                else:
                    key = frozenset(bundle.items())
                    if key not in self._warned_infeasible:
                        self._warned_infeasible.add(key)
                        logger.warning(
                            "demand bundle %s cannot fit the configured "
                            "node shape %s; ignoring it", bundle,
                            self.node_config.get("resources"))
        # Nodes already launched but not yet registered count toward the
        # demand (launch-in-flight; re-launching per tick would stampede).
        in_flight = len(managed) - len(groups)
        needed_for_demand = max(0, self._pack_nodes_needed(unfit) - in_flight)

        # 2) utilization pressure. Suppressed while a launch is in flight:
        #    a cloud node takes minutes to bootstrap and ticks are seconds —
        #    without the gate, sustained pressure launches a node per tick.
        total = sum(n.resources.get("CPU", 0) for n in nodes)
        free = sum(n.available.get("CPU", 0) for n in nodes)
        util = 1.0 - (free / total) if total else 0.0
        pressure = 1 if util > self.target_utilization and in_flight == 0 \
            else 0

        want = max(self.min_workers,
                   len(managed) + needed_for_demand + pressure)
        want = min(want, self.max_workers)

        from ray_tpu._private import metrics_defs as mdefs

        while len(self.provider.non_terminated_nodes()) < want:
            if time.monotonic() < self._alloc_backoff_until:
                break  # allocation-failure backoff window still open
            if self.im.launch_instances(1, self.node_config):
                launched += 1
                self._alloc_fail_streak = 0
            else:
                # Allocation failed: count it, open/extend the
                # exponential backoff window, and stop launching this
                # tick (retrying at full rate next tick is exactly the
                # provider-hammering this backoff exists to prevent).
                self._alloc_fail_streak += 1
                mdefs.AUTOSCALER_ALLOC_FAILURES.inc(
                    tags={"provider": self._provider_tag})
                backoff = min(
                    self._alloc_backoff_base_s *
                    2 ** (self._alloc_fail_streak - 1),
                    self._alloc_backoff_max_s)
                self._alloc_backoff_until = time.monotonic() + backoff
                logger.warning(
                    "allocation failed (streak %d); backing launches "
                    "off %.1fs", self._alloc_fail_streak, backoff)
                break

        now = time.monotonic()
        # Retry instances stuck TERMINATING (an earlier provider
        # terminate call failed transiently).
        from ray_tpu.autoscaler import instance_manager as im_mod

        for inst in self.im.instances({im_mod.TERMINATING}):
            if self.im.terminate_instance(inst.instance_id,
                                          "retry terminate"):
                terminated += 1

        # 3) reclaim provider nodes whose bootstrap never registered.
        managed_now = set(self.provider.non_terminated_nodes())
        for pid in list(self._unregistered_since):
            if pid not in managed_now:  # vanished externally: don't leak
                self._unregistered_since.pop(pid, None)
        for pid in managed_now:
            if pid in groups:
                self._unregistered_since.pop(pid, None)
                continue
            first = self._unregistered_since.setdefault(pid, now)
            if now - first > self.UNREGISTERED_GRACE_S:
                logger.warning("provider node %s never registered; "
                               "terminating", pid)
                if self._terminate_pid(pid, "bootstrap never registered"):
                    self._unregistered_since.pop(pid, None)
                    terminated += 1

        # 4) scale down: provider nodes whose EVERY host is fully idle
        #    past the timeout (one busy host keeps the whole slice).
        if not unfit and pressure == 0:
            over = len(self.provider.non_terminated_nodes()) - max(
                self.min_workers, 0)
            for pid, hosts in groups.items():
                if over <= 0:
                    break
                fully_idle = all(
                    abs(h.available.get(k, 0.0) - v) < 1e-6
                    for h in hosts for k, v in h.resources.items())
                if fully_idle:
                    first = self._idle_since.setdefault(pid, now)
                    if now - first > self.idle_timeout_s and \
                            self._terminate_pid(pid, "idle past timeout"):
                        self._idle_since.pop(pid, None)
                        terminated += 1
                        over -= 1
                else:
                    self._idle_since.pop(pid, None)
        self._publish_status()
        return {"launched": launched, "terminated": terminated,
                "instances": self.im.summary()}

    def summary(self) -> Dict[str, Any]:
        """Operator/dashboard view of reconciler health: the instance
        table plus the failure accounting (_loop streaks, allocation
        backoff, last tick error) that would otherwise live only in the
        head-node log."""
        now = time.monotonic()
        return {
            "instances": self.im.summary(),
            "provider": self._provider_tag,
            "consecutive_tick_failures": self._tick_fail_streak,
            "last_tick_error": self._last_tick_error,
            "allocation_failure_streak": self._alloc_fail_streak,
            "allocation_backoff_remaining_s": round(
                max(self._alloc_backoff_until - now, 0.0), 3),
            "tick_interval_s": self._effective_interval(),
        }

    def _publish_status(self) -> None:
        """Mirror summary() into the GCS KV so the dashboard — which
        talks to the GCS, not to this process — can render autoscaler
        health without a runtime. Best-effort."""
        try:
            self.gcs.KvPut(pb.KvRequest(
                ns=KV_NS, key="status",
                value=json.dumps({"ts": time.time(),
                                  **self.summary()}).encode(),
                overwrite=True))
        except Exception:  # noqa: BLE001 — monitoring mirror only
            pass

    def _terminate_pid(self, provider_id: str, detail: str) -> bool:
        """Terminate through the instance table when this reconciler
        launched the node; directly otherwise (e.g. a pre-existing
        provider node carrying our cluster label). Returns success — a
        failed provider call leaves the instance TERMINATING and the
        caller must NOT count it terminated or drop its trackers."""
        inst = self.im.get_by_provider_id(provider_id)
        if inst is not None:
            return self.im.terminate_instance(inst.instance_id, detail)
        try:
            self.provider.terminate_node(provider_id)
            return True
        except Exception as e:  # noqa: BLE001
            logger.warning("terminate of unmanaged %s failed: %s",
                           provider_id, e)
            return False

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    TICK_BACKOFF_MAX_FACTOR = 8

    def _effective_interval(self) -> float:
        """Tick interval with failure backoff: a streak of raised ticks
        (GCS unreachable, provider API down) doubles the interval up to
        a cap instead of spinning the failing call at full rate."""
        return self.tick_interval_s * min(
            2 ** self._tick_fail_streak, self.TICK_BACKOFF_MAX_FACTOR)

    def _loop(self):
        from ray_tpu._private import metrics_defs as mdefs

        while not self._stop.wait(self._effective_interval()):
            try:
                self.reconcile_once()
                self._tick_fail_streak = 0
                self._last_tick_error = None
            except Exception as e:  # noqa: BLE001
                # Swallowing alone loses the failure: count the streak
                # into the gauge, keep the last error for summary()/the
                # dashboard, and let _effective_interval back off.
                self._tick_fail_streak += 1
                self._last_tick_error = f"{type(e).__name__}: {e}"
                logger.exception("autoscaler tick failed (streak %d)",
                                 self._tick_fail_streak)
                self._publish_status()
            mdefs.AUTOSCALER_TICK_FAILURES.set(
                float(self._tick_fail_streak),
                tags={"provider": self._provider_tag})

    def stop(self):
        self._stop.set()


__all__ = ["Autoscaler", "FakeNodeProvider", "NodeProvider",
           "request_resources"]
