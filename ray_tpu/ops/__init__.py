"""TPU compute ops: attention kernels, sequence parallelism, MoE, norms."""

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.decode_attention import (decode_attention,
                                          decode_attention_reference)
from ray_tpu.ops.moe import init_moe_params, moe_layer, router_topk
from ray_tpu.ops.norms import layer_norm, rms_norm
from ray_tpu.ops.ring_attention import ring_attention, ulysses_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "apply_rope", "decode_attention", "decode_attention_reference",
    "flash_attention", "init_moe_params", "layer_norm",
    "mha_reference", "moe_layer", "ring_attention", "rms_norm",
    "rope_frequencies", "router_topk", "ulysses_attention",
]
