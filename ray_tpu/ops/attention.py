"""Attention ops: JAX reference MHA/GQA + pallas TPU flash attention.

The reference framework has no attention kernels of its own (it hosts engines
that bring them — SURVEY.md §2.3); a TPU-native training/serving framework
must supply them. Design follows the blockwise online-softmax scheme
(Flash Attention) tiled for the MXU:

* forward: grid ``(batch, q_heads, q_blocks, k_blocks)`` — the innermost grid
  dimension runs sequentially on TPU, so the running max / sum / accumulator
  live in VMEM scratch carried across k-blocks.
* backward: one pass for dq (grid over k inside), one for dk/dv (grid over q
  inside), with the standard ``delta = rowsum(dO * O)`` precomputation.
* GQA is expressed in the BlockSpec index maps (kv head = q head // group) —
  K/V are never materialized per-q-head.

Public entry :func:`flash_attention` is shape-polymorphic over GQA and
dispatches to the pallas kernel on TPU, and to the fused-by-XLA reference
implementation elsewhere (CPU tests run the kernel in interpret mode).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds of jax as well
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ray_tpu.ops.decode_attention import _interpret_default

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


# ---------------------------------------------------------------------------
# Reference implementation (also the CPU path; XLA fuses it adequately there).
# ---------------------------------------------------------------------------

def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Plain attention. q: [B, Sq, Hq, D]; k/v: [B, Sk, Hkv, D] (GQA ok)."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    group = hq // hkv
    qg = q.reshape(b, sq, hkv, group, d)
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None, None], logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v.astype(jnp.float32))
    return out.reshape(b, sq, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU)
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, num_k_blocks, offs):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # For causal masks, k-blocks strictly above the diagonal contribute
    # nothing. `offs = sk - sq` aligns the mask bottom-right (matching
    # mha_reference's tril(k=sk-sq)) so sq != sk decode/chunked shapes work.
    run = (ik * block_k <= iq * block_q + block_q - 1 + offs) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                    # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (iq * block_q + rows + offs) >= (ik * block_k + cols)
            s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_prev = m_ref[:, :1]                                # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                               # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                      # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, 0].astype(jnp.float32)                  # [bk, d]
        pv = jnp.dot(p, v, preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        # Fully-masked rows (possible with padding) have l == 0; emit zeros.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, :1] + jnp.log(safe_l)


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    nq, nk = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)

    grid = (b, hq, nq, nk)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda b_, h, i, j: (b_, h // group, j, 0))
    out_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0))
    # lse kept as [B, H, S, 1]: block last-two dims (block_q, 1) satisfy the
    # TPU tiling rule (sublane multiple of 8, lane == full array dim).
    lse_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i, j: (b_, h, i, 0))

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=nk, offs=sk - sq,
    )
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
    ]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[out_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * hq * sq * sk * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * hq * sq * sk,
        ),
    )(q, k, v)
    return out, lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
               *, scale, causal, block_q, block_k, num_k_blocks, offs):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (ik * block_k <= iq * block_q + block_q - 1 + offs) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        lse = lse_ref[0, 0]                                   # [bq, 1]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (iq * block_q + rows + offs) >= (ik * block_k + cols)
            s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # [bq, bk]
        acc_ref[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_k, num_q_blocks, offs):
    ik, iq = pl.program_id(2), pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq * block_q + block_q - 1 + offs >= ik * block_k) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * scale           # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                   # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (iq * block_q + rows + offs) >= (ik * block_k + cols)
            s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # [bq, bk]
        # dk += ds^T @ q  (q already carries `scale`)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(res, g, *, scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse = res
    do = g
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    nq, nk = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)

    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0))
    kv_spec_dq = pl.BlockSpec((1, 1, block_k, d),
                              lambda b_, h, i, j: (b_, h // group, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i, j: (b_, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          offs=sk - sq),
        grid=(b, hq, nq, nk),
        in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid over q-heads; each q-head contributes to its kv head. To
    # keep the accumulation race-free we compute per-q-head dk/dv and sum the
    # group afterwards (cheap: [b, hq, sk, d] f32 intermediate).
    q_spec2 = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, j, i: (b_, h, i, 0))
    kv_spec2 = pl.BlockSpec((1, 1, block_k, d),
                            lambda b_, h, j, i: (b_, h // group, j, 0))
    kv_out_spec = pl.BlockSpec((1, 1, block_k, d),
                               lambda b_, h, j, i: (b_, h, j, 0))
    row_spec2 = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, j, i: (b_, h, i, 0))

    dk_ph, dv_ph = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          offs=sk - sq),
        grid=(b, hq, nk, nq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[kv_out_spec, kv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk = dk_ph.reshape(b, hkv, group, sk, d).sum(axis=2).astype(k.dtype)
    dv = dv_ph.reshape(b, hkv, group, sk, d).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, res, g):
    return _bwd(res, g, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, interpret=interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_applicable(
    sq: int, sk: int, d: int, *, causal: bool = True,
    block_q: int = 1024, block_k: int = 1024,
) -> bool:
    """True when :func:`flash_attention` takes the pallas kernel path for
    these shapes (vs the XLA reference fallback). Kept next to the kernel so
    diagnostics (bench.py) can't drift from the real dispatch predicate."""
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    return not (
        sq < 8 or sq % block_q or sk % block_k or d % 128 or pltpu is None
        or (causal and sq > sk)
    )


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention. Layout [B, S, H, D]; supports GQA (Hkv divides Hq).

    Falls back to :func:`mha_reference` when the sequence doesn't tile
    (shorter than one block) — XLA handles those sizes well natively.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if not flash_applicable(sq, sk, d, causal=causal,
                            block_q=block_q, block_k=block_k):
        # Tiny-q (decode), non-tiling shapes, or causal-with-fewer-keys (rows
        # would be fully masked): XLA handles these well natively.
        return mha_reference(q, k, v, causal=causal, scale=scale)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret is None:
        # RAY_TPU_PALLAS_INTERPRET overrides (the pallas_interpret test
        # fixture), else interpret everywhere but real TPU.
        interpret = _interpret_default()

    # Kernels use [B, H, S, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, scale, causal, block_q, block_k, interpret)
    return out.transpose(0, 2, 1, 3)
