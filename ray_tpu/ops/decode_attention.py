"""Fused pallas decode attention: per-slot single-query GQA over a KV pool.

The continuous-batching decode tick attends ONE query token per slot
against that slot's cached prefix — the serving hot loop is pure HBM
bandwidth: read the KV prefixes once, emit [B, H, D]. The XLA reference
path (:func:`decode_attention_reference`, the engine's original
``_attend_decode``) upcasts the full ``[B, S_max, KVH, D]`` cache to fp32
and materializes it twice per layer (QK^T and PV see separate fp32
copies), tripling the bytes moved per tick. This kernel fuses the length
mask, online softmax, and PV product into one pass that streams K and V
through VMEM in their storage dtype (bf16 on TPU) with fp32 accumulation.

Structure mirrors ``ops/attention.py``: grid ``(batch, kv_heads,
k_blocks)`` with the innermost dimension sequential on TPU so the running
max / sum / accumulator live in VMEM scratch; GQA keeps the query group
``[G, D]`` resident per program (G = Hq // Hkv), so K/V are read exactly
once per kv head. Per-slot lengths arrive as scalars in SMEM and gate
both the block grid (blocks wholly past a slot's position are skipped)
and the in-block mask.

Dispatch: :func:`decode_attention` runs the kernel on TPU when the
shapes tile, interpret mode when forced (CPU tier-1 tests), and the XLA
reference otherwise. ``RAY_TPU_PALLAS_INTERPRET=1`` forces interpret
mode globally (the ``pallas_interpret`` conftest fixture).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds of jax as well
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# The reference masks with -1e30 (not -inf: fully-masked garbage rows in
# inactive slots must softmax to finite values, not NaN). Kept identical
# here so kernel-on/off greedy decode stays token-for-token stable.
MASK_VALUE = -1e30


def env_flag(name: str) -> Optional[bool]:
    """Tri-state env knob: '1'/'true'/'on' -> True, '0'/'false'/'off' ->
    False, unset/other -> None (auto)."""
    val = os.environ.get(name, "").strip().lower()
    if val in ("1", "true", "on", "yes"):
        return True
    if val in ("0", "false", "off", "no"):
        return False
    return None


def _interpret_default() -> bool:
    forced = env_flag("RAY_TPU_PALLAS_INTERPRET")
    if forced is not None:
        return forced
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Reference (the engine's original _attend_decode; also the CPU path).
# ---------------------------------------------------------------------------

def decode_attention_reference(q, cache_k, cache_v, positions,
                               scale: Optional[float] = None):
    """Single-token attention with per-slot positions.

    q [B, H, D]; cache [B, S_max, KVH, D]; positions [B] (the absolute
    position each slot's query occupies).
    """
    b, hq, d = q.shape
    s_max, hkv = cache_k.shape[1], cache_k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    group = hq // hkv
    qg = q.reshape(b, hkv, group, d).astype(jnp.float32)
    logits = jnp.einsum("bhgd,bkhd->bhgk", qg,
                        cache_k.astype(jnp.float32)) * scale
    slots = jnp.arange(s_max)
    mask = positions[:, None] >= slots[None, :]             # [B, S_max]
    logits = jnp.where(mask[:, None, None, :], logits, MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgk,bkhd->bhgd", probs,
                     cache_v.astype(jnp.float32))
    return out.reshape(b, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, scale, block_k, num_k_blocks):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # The query sits at absolute position `pos`; cache entries at
    # [0..pos] are live. Blocks strictly past it contribute nothing.
    pos = pos_ref[0]
    run = ik * block_k <= pos

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)              # [G, D]
        k = k_ref[0, :, 0].astype(jnp.float32)           # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [G, bk]
        g = s.shape[0]
        cols = jax.lax.broadcasted_iota(jnp.int32, (g, block_k), 1)
        s = jnp.where(pos >= ik * block_k + cols, s, MASK_VALUE)

        m_prev = m_ref[:, :1]                            # [G, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # [G, bk]
        alpha = jnp.exp(m_prev - m_new)                  # [G, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, :, 0].astype(jnp.float32)           # [bk, D]
        pv = jnp.dot(p, v, preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        # Position 0 is always live, so l > 0 for every real slot; guard
        # anyway so padded grid rows emit zeros rather than NaN.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _decode_fused(q, cache_k, cache_v, positions, *, scale, block_k,
                  interpret):
    b, hq, d = q.shape
    s_max, hkv = cache_k.shape[1], cache_k.shape[2]
    group = hq // hkv
    nk = pl.cdiv(s_max, block_k)

    qg = q.reshape(b, hkv, group, d)
    grid = (b, hkv, nk)
    pos_spec = pl.BlockSpec((1,), lambda b_, h, j: (b_,),
                            memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, 1, group, d), lambda b_, h, j: (b_, h, 0, 0))
    kv_spec = pl.BlockSpec((1, block_k, 1, d),
                           lambda b_, h, j: (b_, j, h, 0))
    out_spec = pl.BlockSpec((1, 1, group, d), lambda b_, h, j: (b_, h, 0, 0))

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, num_k_blocks=nk)
    itemsize = jnp.dtype(cache_k.dtype).itemsize
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pos_spec, q_spec, kv_spec, kv_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            # One query row per slot: 2 matmuls over the live prefix.
            flops=4 * b * hq * s_max * d,
            bytes_accessed=(cache_k.size + cache_v.size) * itemsize
            + q.size * jnp.dtype(q.dtype).itemsize,
            transcendentals=b * hq * s_max,
        ),
    )(positions.astype(jnp.int32), qg, cache_k, cache_v)
    return out.reshape(b, hq, d)


def decode_applicable(s_max: int, d: int, hq: int, hkv: int, *,
                      block_k: int = 512) -> bool:
    """True when :func:`decode_attention` auto-dispatch takes the fused
    kernel for these shapes on TPU (vs the XLA reference). Kept next to
    the kernel so diagnostics (bench_serve.py) can't drift from the real
    dispatch predicate."""
    return not (
        pltpu is None or hq % hkv or d % 128
        or s_max % min(block_k, s_max)
    )


def decode_attention(
    q: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    positions: jnp.ndarray,
    scale: Optional[float] = None,
    *,
    block_k: int = 512,
    use_kernel: Optional[bool] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Decode-step attention. q [B, Hq, D]; cache [B, S_max, Hkv, D]
    (GQA ok); positions [B] = each slot's current absolute position.

    ``use_kernel``: None = auto (fused kernel on TPU when the shapes
    tile, XLA reference elsewhere); True forces the kernel (interpret
    mode off-TPU — how tier-1 CPU tests exercise it); False forces the
    reference.
    """
    b, hq, d = q.shape
    s_max, hkv = cache_k.shape[1], cache_k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if use_kernel is None:
        use_kernel = (jax.default_backend() == "tpu"
                      and decode_applicable(s_max, d, hq, hkv,
                                            block_k=block_k))
    elif use_kernel and pltpu is None:
        # Forcing the kernel on a jax build without pallas-TPU support
        # must fail loudly: a silent reference fallback would make
        # parity tests pass vacuously and perf flags lie.
        raise RuntimeError(
            "decode_attention(use_kernel=True) needs "
            "jax.experimental.pallas.tpu, which this jax build lacks")
    if not use_kernel:
        return decode_attention_reference(q, cache_k, cache_v, positions,
                                          scale)
    if interpret is None:
        interpret = _interpret_default()
    return _decode_fused(q, cache_k, cache_v, positions, scale=scale,
                         block_k=min(block_k, s_max), interpret=interpret)
