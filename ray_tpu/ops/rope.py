"""Rotary position embeddings (RoPE), Llama convention.

Frequencies are computed once per step in fp32 and applied to q/k. The
half-split rotation (rotate_half) is used rather than interleaved pairs —
it lowers to two slices + concat which XLA vectorizes cleanly on the VPU.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    max_len: int,
    theta: float = 10000.0,
    *,
    positions: jnp.ndarray | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Return (cos, sin) of shape [max_len, head_dim//2] (fp32)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if positions is None:
        positions = jnp.arange(max_len, dtype=jnp.float32)
    angles = jnp.outer(positions.astype(jnp.float32), inv_freq)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Apply RoPE to ``x`` of shape [..., seq, heads, head_dim].

    ``cos``/``sin`` have shape [seq, head_dim//2] (broadcast over batch/heads).
    """
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[:, None, :]
    sin = sin[:, None, :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(dtype)
