"""Paged decode attention: block-table gather over a shared KV arena.

The dense fused kernel (``ops/decode_attention.py``) still streams each
slot's full ``S_max`` stripe of the pooled cache per tick — a slot 40
tokens into a 512-token cache pays for 512. Here the pooled cache is an
ARENA of fixed-size blocks (``[num_blocks, block_size, KVH, D]``) and
each slot owns a small BLOCK TABLE naming the blocks it has actually
filled, so a tick reads only live prefix blocks (vLLM paged-attention,
on TPU: block tables ride scalar prefetch so the BlockSpec ``index_map``
can gather arena blocks by table lookup before the kernel body runs).

Two bandwidth levers stack:

* **Paging** — grid ``(batch, kv_heads, table_blocks)`` with dead table
  entries repeating the last live block: pallas skips the re-fetch when
  the mapped block index does not change between sequential grid steps,
  so a slot's dead tail costs ~zero HBM traffic (and ``pl.when`` skips
  its compute).
* **int8 KV quantization** — the arena stores K/V as int8 with
  per-token/per-kv-head fp32 scales kept in block-shaped sidecars
  (``[num_blocks, block_size, KVH]``), gathered by the same table;
  dequantization happens in-register after the block is resident, so
  bytes-per-token roughly halve against bf16.

Same online-softmax core as the dense kernel: fp32 accumulation with a
running max/sum in VMEM scratch; per-slot positions arrive via scalar
prefetch and gate both block skip and the in-block causal mask.

Dispatch mirrors ``decode_attention``: kernel on TPU when shapes tile,
interpret mode when forced (CPU tier-1), XLA reference otherwise.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ray_tpu.ops.decode_attention import (MASK_VALUE, _interpret_default,
                                          pltpu)


def dequantize_block(x, scale):
    """int8 block + per-token/per-head scale -> fp32. ``x`` [..., T, H, D],
    ``scale`` [..., T, H]."""
    return x.astype(jnp.float32) * scale[..., None]


def gather_kv(arena, tables):
    """Linearize a slot's blocks: arena [NB, bs, KVH, D] gathered through
    tables [B, nb] -> [B, nb*bs, KVH, D] (the dense-layout view the
    reference path attends over)."""
    b, nb = tables.shape
    bs = arena.shape[1]
    g = arena[tables]                       # [B, nb, bs, KVH, D]
    return g.reshape(b, nb * bs, *arena.shape[2:])


def paged_attention_reference(q, arena_k, arena_v, tables, positions,
                              scale: Optional[float] = None, *,
                              k_scale=None, v_scale=None):
    """XLA reference: gather blocks into dense layout, dequantize when the
    arena is quantized, then run the positional-mask softmax attention.

    q [B, Hq, D]; arena [NB, bs, KVH, D]; tables [B, nb] (row j = slot's
    j-th logical block; dead entries may repeat blocks — masked out by
    ``positions``); positions [B].
    """
    from ray_tpu.ops.decode_attention import decode_attention_reference

    ck = gather_kv(arena_k, tables)
    cv = gather_kv(arena_v, tables)
    if k_scale is not None:
        ck = dequantize_block(ck, gather_kv(k_scale[..., None],
                                            tables)[..., 0])
        cv = dequantize_block(cv, gather_kv(v_scale[..., None],
                                            tables)[..., 0])
    return decode_attention_reference(q, ck, cv, positions,
                                      scale).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _paged_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                  scale, block_size, num_blocks, quantized):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # The slot's query sits at absolute position `pos`; logical blocks
    # wholly past it are dead (their table entries repeat the last live
    # block, so the pipeline fetches nothing new for them either).
    pos = pos_ref[b]
    run = j * block_size <= pos

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)              # [G, D]
        k = k_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
        if quantized:
            k = k * ks_ref[0, :, 0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [G, bs]
        g = s.shape[0]
        cols = jax.lax.broadcasted_iota(jnp.int32, (g, block_size), 1)
        s = jnp.where(pos >= j * block_size + cols, s, MASK_VALUE)

        m_prev = m_ref[:, :1]                            # [G, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # [G, bs]
        alpha = jnp.exp(m_prev - m_new)                  # [G, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
        if quantized:
            v = v * vs_ref[0, :, 0].astype(jnp.float32)[:, None]
        pv = jnp.dot(p, v, preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _paged_fused(q, arena_k, arena_v, tables, positions, *, k_scale,
                 v_scale, scale, interpret):
    b, hq, d = q.shape
    _, block_size, hkv, _ = arena_k.shape
    nb = tables.shape[1]
    group = hq // hkv
    quantized = k_scale is not None

    qg = q.reshape(b, hkv, group, d)
    q_spec = pl.BlockSpec((1, 1, group, d),
                          lambda b_, h, j, tab, po: (b_, h, 0, 0))
    # The table gather IS the index_map: scalar-prefetched block tables
    # choose which arena block each grid step streams into VMEM.
    kv_spec = pl.BlockSpec((1, block_size, 1, d),
                           lambda b_, h, j, tab, po: (tab[b_, j], 0, h, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qg, arena_k, arena_v]
    if quantized:
        sc_spec = pl.BlockSpec((1, block_size, 1),
                               lambda b_, h, j, tab, po: (tab[b_, j], 0, h))
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale, v_scale]
    out_spec = pl.BlockSpec((1, 1, group, d),
                            lambda b_, h, j, tab, po: (b_, h, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, nb),
        in_specs=in_specs,
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel, scale=scale, block_size=block_size, num_blocks=nb,
        quantized=quantized)
    itemsize = jnp.dtype(arena_k.dtype).itemsize
    # Grid (b, hkv, nb): every kv head re-streams its [bs, d] slice of
    # each table block, so worst-case KV traffic carries the hkv factor.
    kv_bytes = 2 * b * hkv * nb * block_size * d * itemsize
    if quantized:
        kv_bytes += 2 * b * hkv * nb * block_size * 4    # fp32 scales
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            # Static worst case: every table entry live. The engine feeds
            # the monitor a live-token byte estimate for achieved-BW.
            flops=4 * b * hq * nb * block_size * d,
            bytes_accessed=kv_bytes
            + q.size * jnp.dtype(q.dtype).itemsize,
            transcendentals=b * hq * nb * block_size,
        ),
    )(tables.astype(jnp.int32), positions.astype(jnp.int32), *inputs)
    return out.reshape(b, hq, d)


def paged_applicable(block_size: int, d: int, hq: int, hkv: int) -> bool:
    """True when auto-dispatch takes the paged fused kernel on TPU for
    these shapes (lane-tiling head_dim, sublane-tiling blocks, whole
    query groups)."""
    return not (pltpu is None or hq % hkv or d % 128 or block_size % 32)


def paged_decode_attention(
    q: jnp.ndarray,
    arena_k: jnp.ndarray,
    arena_v: jnp.ndarray,
    tables: jnp.ndarray,
    positions: jnp.ndarray,
    scale: Optional[float] = None,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    use_kernel: Optional[bool] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Decode-step attention over a paged KV arena.

    q [B, Hq, D]; arena_k/v [NB, bs, KVH, D] (int8 when ``k_scale`` /
    ``v_scale`` [NB, bs, KVH] are given); tables [B, nb] int32 block
    table (row j = the slot's j-th logical block; dead tail entries
    should repeat the last live block); positions [B].

    ``use_kernel``: None = auto (fused kernel on TPU when the shapes
    tile, XLA reference elsewhere); True forces the kernel (interpret
    mode off-TPU — the CPU tier-1 path); False forces the reference.
    """
    b, hq, d = q.shape
    block_size, hkv = arena_k.shape[1], arena_k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if use_kernel is None:
        use_kernel = (jax.default_backend() == "tpu"
                      and paged_applicable(block_size, d, hq, hkv))
    elif use_kernel and pltpu is None:
        raise RuntimeError(
            "paged_decode_attention(use_kernel=True) needs "
            "jax.experimental.pallas.tpu, which this jax build lacks")
    if not use_kernel:
        return paged_attention_reference(q, arena_k, arena_v, tables,
                                         positions, scale,
                                         k_scale=k_scale, v_scale=v_scale)
    if interpret is None:
        interpret = _interpret_default()
    return _paged_fused(q, arena_k, arena_v, tables, positions,
                        k_scale=k_scale, v_scale=v_scale, scale=scale,
                        interpret=interpret)
