"""Mixture-of-experts layer with expert parallelism.

The reference has no MoE of its own (experts arrive via hosted engines —
SURVEY.md §2.3); ray_tpu provides EP natively as the ``expert`` mesh axis:

* router: top-k softmax gating (jittable, static shapes);
* dispatch: capacity-bounded one-hot combine — tokens over capacity drop
  (standard Switch/GShard semantics) so shapes stay static for XLA;
* expert compute: experts stacked on a leading axis sharded over the
  ``expert`` mesh axis; dispatch/combine einsums become all-to-alls on ICI
  when sharded (XLA inserts them from the shardings — the
  ``ragged_all_to_all`` of SURVEY §2.3 expressed GSPMD-style).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def router_topk(
    gate_logits: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k gating. gate_logits [T, E] → (weights [T, k], idx [T, k])."""
    weights, idx = jax.lax.top_k(gate_logits, k)
    weights = jax.nn.softmax(weights, axis=-1)
    return weights, idx


def dispatch_mask(
    expert_idx: jnp.ndarray, num_experts: int, capacity: int
) -> jnp.ndarray:
    """[T, k] expert ids → dispatch tensor [T, E, C] (0/1).

    Position within an expert's buffer = running count of tokens routed to
    that expert; tokens beyond ``capacity`` are dropped (their row is zero).
    """
    t, k = expert_idx.shape
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.int32)  # [T,k,E]
    flat = onehot.reshape(t * k, num_experts)
    position = jnp.cumsum(flat, axis=0) - 1                  # slot per token
    in_cap = position < capacity
    slot_onehot = jax.nn.one_hot(position, capacity, dtype=jnp.float32)
    disp = (flat[..., None] * in_cap[..., None] * slot_onehot)
    return disp.reshape(t, k, num_experts, capacity).sum(axis=1)


def moe_layer(
    x: jnp.ndarray,
    params: Dict[str, jnp.ndarray],
    *,
    num_experts: int,
    top_k: int = 2,
    capacity_factor: float = 1.25,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Apply a SwiGLU MoE block. x: [B, S, E_model].

    params: ``w_router`` [E_model, E], stacked expert weights ``w_gate`` /
    ``w_up`` [E, E_model, M] and ``w_down`` [E, M, E_model] (leading axis
    logical name "experts" → shard over the ``expert`` mesh axis).
    Returns (output, aux) where aux carries the load-balancing loss.
    """
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    t = tokens.shape[0]
    capacity = max(int(capacity_factor * t * top_k / num_experts), top_k)

    gate_logits = tokens.astype(jnp.float32) @ params["w_router"].astype(
        jnp.float32)
    weights, idx = router_topk(gate_logits, top_k)
    disp = dispatch_mask(idx, num_experts, capacity)          # [T, E, C]

    # Expert buffers: [E, C, D] — this einsum is the dispatch all-to-all when
    # tokens are batch-sharded and experts are expert-sharded.
    expert_in = jnp.einsum("tec,td->ecd", disp, tokens.astype(jnp.float32))
    expert_in = expert_in.astype(x.dtype)

    def expert_fn(buf, wg, wu, wd):
        act = jax.nn.silu(buf @ wg) * (buf @ wu)
        return act @ wd

    expert_out = jax.vmap(expert_fn)(
        expert_in, params["w_gate"].astype(x.dtype),
        params["w_up"].astype(x.dtype), params["w_down"].astype(x.dtype))

    # Combine weights: scatter the router weight of each kept (token, expert).
    w_per_expert = jnp.einsum(
        "tke,tk->te", jax.nn.one_hot(idx, num_experts, dtype=jnp.float32),
        weights)
    combine = disp * w_per_expert[:, :, None]                 # [T, E, C]
    out = jnp.einsum("tec,ecd->td", combine,
                     expert_out.astype(jnp.float32))

    # Load-balancing aux loss (Switch Transformer eq. 4).
    probs = jax.nn.softmax(gate_logits, axis=-1)
    frac_tokens = jnp.mean(
        jax.nn.one_hot(idx[:, 0], num_experts, dtype=jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux_loss = num_experts * jnp.sum(frac_tokens * frac_probs)

    return out.reshape(b, s, d).astype(x.dtype), {
        "aux_loss": aux_loss,
        "dropped_fraction": 1.0 - jnp.sum(disp) / (t * top_k),
    }


def init_moe_params(
    key: jax.Array, d_model: int, d_ff: int, num_experts: int,
    dtype=jnp.bfloat16,
) -> Dict[str, jnp.ndarray]:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale_in = d_model ** -0.5
    scale_out = d_ff ** -0.5
    return {
        "w_router": (jax.random.normal(k1, (d_model, num_experts))
                     * scale_in).astype(jnp.float32),
        "w_gate": (jax.random.normal(k2, (num_experts, d_model, d_ff))
                   * scale_in).astype(dtype),
        "w_up": (jax.random.normal(k3, (num_experts, d_model, d_ff))
                 * scale_in).astype(dtype),
        "w_down": (jax.random.normal(k4, (num_experts, d_ff, d_model))
                   * scale_out).astype(dtype),
    }


MOE_LOGICAL_AXES = {
    "w_router": ("embed", None),
    "w_gate": ("experts", "embed", "mlp"),
    "w_up": ("experts", "embed", "mlp"),
    "w_down": ("experts", "mlp", "embed"),
}
