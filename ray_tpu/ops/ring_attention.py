"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context support of its own (SURVEY.md §5 —
"absent in the reference"); this module provides it natively, TPU-first:

* :func:`ring_attention` — blockwise attention where K/V shards rotate
  around the ``seq`` mesh axis via ``jax.lax.ppermute`` (nearest-neighbor on
  the ICI torus) while each step's partial softmax is merged online. Peak
  memory per chip is O((S/N)^2) logits + one in-flight K/V shard, and the
  permute overlaps with the block compute (XLA schedules the collective
  asynchronously).
* :func:`ulysses_attention` — all-to-all head-scatter/seq-gather: resharding
  [B, S/N, H, D] → [B, S, H/N, D], running dense (flash) attention on full
  sequences for a subset of heads, and resharding back.

Both are meant to be called INSIDE ``shard_map`` over a mesh with a ``seq``
axis; :func:`ray_tpu.models` wires them into the flagship model when the
mesh has seq > 1.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def _block_attend(q, k, v, q_pos, k_pos, scale, causal):
    """One blockwise partial-attention step.

    q: [B, Sq, Hq, D]; k/v: [B, Sk, Hkv, D]; positions are global token ids.
    Returns unnormalized (acc [B, Sq, Hq, D] f32, m, l [B, Sq, Hq, 1] f32).
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    group = hq // hkv
    qg = q.reshape(b, sq, hkv, group, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bqhgk", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]            # [Sq, Sk]
        s = jnp.where(mask[None, :, None, None, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)                 # [b,sq,hkv,g,1]
    # Rows with no visible keys in this block: exp(-inf - -inf) guards.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bqhgk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return (
        acc.reshape(b, sq, hq, d),
        m_safe.reshape(b, sq, hq, 1),
        l.reshape(b, sq, hq, 1),
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Ring attention over a sharded sequence axis (call inside shard_map).

    ``q/k/v``: local shards [B, S_local, H, D] ([B, S_local, Hkv, D] for k/v);
    the global sequence is the concatenation over ``axis_name`` in mesh order.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    q_pos = my_idx * s_local + jnp.arange(s_local)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        acc, m, l, k_cur, v_cur = carry
        src_chunk = (my_idx - i) % axis_size
        k_pos = src_chunk * s_local + jnp.arange(s_local)
        a, m_blk, l_blk = _block_attend(q, k_cur, v_cur, q_pos, k_pos, scale, causal)
        # Merge online-softmax partials. Blocks fully above the causal
        # diagonal produce l_blk == 0 and contribute nothing.
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_blk - m_new)
        acc = acc * alpha + a * beta
        l = l * alpha + l_blk * beta
        # Rotate K/V to the next ring position (nearest-neighbor on ICI).
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m_new, l, k_next, v_next), None

    b, sq, hq, d = q.shape
    init = (
        jnp.zeros((b, sq, hq, d), jnp.float32),
        jnp.full((b, sq, hq, 1), -jnp.inf, jnp.float32),
        jnp.zeros((b, sq, hq, 1), jnp.float32),
    )
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, init + (k, v), jnp.arange(axis_size)
    )
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
    attn_fn=None,
) -> jnp.ndarray:
    """Ulysses sequence parallelism (call inside shard_map).

    All-to-all converts the sequence sharding into a head sharding, dense
    attention runs over the full sequence for H/N heads, and the result is
    converted back. Requires both Hq and Hkv divisible by the axis size.
    """
    from ray_tpu.ops.attention import flash_attention

    attn_fn = attn_fn or functools.partial(flash_attention)
    # [B, S/N, H, D] -> [B, S, H/N, D]
    qg = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    # [B, S, H/N, D] -> [B, S/N, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
