"""Normalization ops.

Kept as plain jnp: XLA fuses the reduce + rsqrt + scale into the neighboring
matmul's epilogue on TPU, so a hand-written pallas kernel buys nothing here
(HBM-bound either way); kernel effort goes to attention instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm (Llama-style): ``x / rms(x) * weight``, computed in fp32."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
